"""Paged KV cache (ISSUE 8): ref-counted block pool + block tables +
shared-prefix prefill reuse.

Covers the pool/trie bookkeeping (alloc/free/ref counts/COW/eviction,
zero-leak accounting), the block-table operand of the flash-decode
kernel, engine parity against the dense layout and one-shot generate(),
prefix-hit reuse (a templated request takes block references instead of
re-prefilling — and still decodes bit-identically), the stale-KV reuse
invariant for BOTH layouts (a freed block/slot rebound to a new request
is never attendable before that request overwrites it — proven by
poisoning freed storage with NaN), typed block-exhaustion backpressure
(victim retired, batch survives), the ``serve.kv.bind`` fault point,
and a seeded chaos run asserting zero slot AND block leaks with the
frozen program count and schema-valid artifacts.
"""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.models.generate import generate
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import (
    Engine,
    KVBlocksExhausted,
    PagedSlotPool,
    PrefixTrie,
    Request,
    Scheduler,
    ServeConfig,
)

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
# Paged serving shapes: block_size 4 so tiny prompts span real blocks
# (full-block prefix hits, COW, lazy growth all fire at test sizes).
PCFG = ServeConfig(max_batch_size=3, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32, kv_block_size=4)
DCFG = dataclasses.replace(PCFG, kv_layout="dense")

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for sub in ("tools", "benchmarks"):
    p = os.path.join(_ROOT, sub)
    if p not in sys.path:
        sys.path.insert(0, p)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


def _drain(sched, max_iters=400):
    sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"


def _greedy_ref(model, variables, prompt, n):
    return np.asarray(generate(
        model, variables, np.asarray([prompt], np.int32),
        max_new_tokens=n, temperature=0.0,
        cache_dtype=jnp.float32))[0, len(prompt):].tolist()


# ------------------------------------------------------------- the pool
def test_paged_pool_alloc_bind_free_refcounts(model_and_vars):
    model, _ = model_and_vars
    pool = PagedSlotPool(model, capacity=2, max_len=16,
                         dtype=jnp.float32, block_size=4)
    # Dense-equivalent default: 1 scratch + 2 slots * 4 blocks.
    assert pool.num_blocks == 9 and pool.blocks_per_slot == 4
    assert pool.blocks_used == 0
    s = pool.alloc()
    assert pool.bind_for_prompt(s, [1, 2, 3, 4, 5]) == 0  # cold: no hits
    pool.prepare_write(s, 0, 8)        # bind blocks 0..1 of the slot
    assert pool.blocks_used == 2
    assert 0 not in pool.tables_host[s, :2]   # scratch never allocated
    pool.prepare_write(s, 8, 12)       # lazy growth
    assert pool.blocks_used == 3
    pool.leak_check()
    pool.free(s)
    assert pool.blocks_used == 0 and pool.num_free == 2
    assert (pool.tables_host[s] == 0).all()   # table reset to scratch
    with pytest.raises(ValueError, match="double free"):
        pool.free(s)
    with pytest.raises(ValueError, match="out of range"):
        pool.free(7)
    pool.leak_check()
    # Exhaustion is typed: a slot that wants more blocks than exist.
    small = PagedSlotPool(model, capacity=1, max_len=16,
                          dtype=jnp.float32, block_size=4, num_blocks=3)
    t = small.alloc()
    small.prepare_write(t, 0, 8)       # both usable blocks bound
    with pytest.raises(KVBlocksExhausted):
        small.prepare_write(t, 8, 12)
    small.free(t)
    small.leak_check()


def test_prefix_trie_match_insert_evict():
    trie = PrefixTrie(block_size=4)
    refs = {}

    def take(b):
        refs[b] = refs.get(b, 0) + 1

    def release(b):
        refs[b] -= 1

    toks = list(range(12))
    assert trie.match(toks) == []
    assert trie.insert(toks, [10, 11, 12], take) == 3
    assert trie.match(toks) == [10, 11, 12]
    assert trie.match(toks[:8] + [99, 99, 99, 99]) == [10, 11]
    assert trie.match([99] * 12) == []
    # Re-inserting the same path adds nothing (first writer wins).
    assert trie.insert(toks, [20, 21, 22], take) == 0
    assert refs == {10: 1, 11: 1, 12: 1}
    # A diverging suffix shares the matched prefix path.
    toks2 = toks[:8] + [50, 51, 52, 53]
    assert trie.insert(toks2, [10, 11, 30], take) == 1
    assert trie.match(toks2) == [10, 11, 30]
    # Eviction is leaf-first LRU: interior nodes survive their children.
    trie.match(toks)            # touch the 10->11->12 path (newer)
    assert trie.evict(1, release) == 1
    assert refs[30] == 0        # LRU leaf went first
    assert trie.match(toks) == [10, 11, 12]
    assert trie.evict(10, release) == 3
    assert all(v == 0 for v in refs.values()) and len(trie) == 0


def test_flash_decode_block_table_operand_parity():
    """The kernel's paged mode (block-table gather via scalar prefetch)
    matches the dense kernel over an explicit gather — including the
    per-row length skip (length 0 row stays exactly zero)."""
    from nezha_tpu.ops.pallas import flash_decode_attention

    rng = np.random.default_rng(0)
    b, h, d, bs, m, n = 3, 2, 16, 8, 4, 10
    q = jnp.asarray(rng.normal(size=(b, h, 1, d)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n, h, bs, d)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n, h, bs, d)), jnp.float32)
    tables = jnp.asarray(rng.integers(0, n, size=(b, m)), jnp.int32)
    lengths = jnp.asarray([0, 13, 32], jnp.int32)
    paged = flash_decode_attention(q, kp, vp, lengths,
                                   block_tables=tables)
    kd = kp[tables].transpose(0, 2, 1, 3, 4).reshape(b, h, m * bs, d)
    vd = vp[tables].transpose(0, 2, 1, 3, 4).reshape(b, h, m * bs, d)
    dense = flash_decode_attention(q, kd, vd, lengths)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(dense),
                               atol=1e-5)
    assert np.all(np.asarray(paged)[0] == 0.0)   # inactive row
    # Traced tables under jit: same program shape the engine compiles.
    jitted = jax.jit(lambda *a: flash_decode_attention(
        a[0], a[1], a[2], a[3], block_tables=a[4]))
    np.testing.assert_allclose(
        np.asarray(jitted(q, kp, vp, lengths, tables)),
        np.asarray(dense), atol=1e-5)


# --------------------------------------------------------- engine parity
def test_paged_engine_matches_dense_and_generate(model_and_vars):
    """Greedy, sampled, and chunked-prompt requests decode identically
    on the paged and dense layouts, and greedy matches one-shot
    generate() — the block indirection is a memory layout, never a
    semantic. The frozen program count holds for both."""
    model, variables = model_and_vars
    reqs = [dict(prompt=[5, 17, 3, 42], max_new_tokens=10),
            dict(prompt=[7, 7], max_new_tokens=9, temperature=0.9,
                 top_k=10, seed=7),
            dict(prompt=[(7 * i + 3) % 97 for i in range(20)],
                 max_new_tokens=6)]
    outs = {}
    for name, cfg in (("paged", PCFG), ("dense", DCFG)):
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        rids = [sched.submit(Request(**kw)) for kw in reqs]
        _drain(sched)
        outs[name] = [sched.results[r].tokens for r in rids]
        stats = eng.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(cfg.prefill_buckets)
        if name == "paged":
            eng.pool.leak_check()
    assert outs["paged"] == outs["dense"]
    assert outs["paged"][0] == _greedy_ref(model, variables,
                                           reqs[0]["prompt"], 10)
    assert outs["paged"][2] == _greedy_ref(model, variables,
                                           reqs[2]["prompt"], 6)


def test_prefix_hit_skips_prefill_and_decodes_identically(
        model_and_vars, tmp_path):
    """Templated traffic: a request whose prompt shares a cached
    full-block prefix takes references instead of re-prefilling — the
    prefill work drops to the un-cached tail (observable in the chunk
    counter), the hit is counted, and the decoded tokens are identical
    to a cold engine's. Program count stays frozen (partial-prefix
    prefill reuses the same bucket programs)."""
    model, variables = model_and_vars
    prefix = [(3 * i + 5) % 97 for i in range(16)]   # 4 full blocks
    tail_a, tail_b = [33, 44], [55]
    obs.start_run(str(tmp_path / "hits"), meta={"kind": "test"})
    try:
        eng = Engine(model, variables, PCFG)
        sched = Scheduler(eng)
        a = sched.submit(Request(prompt=prefix + tail_a,
                                 max_new_tokens=4))
        _drain(sched)
        assert eng.pool.prefix_hits == 0 and len(eng.pool.trie) == 4
        chunks_cold = obs.counter("serve.prefill.chunks_total").value
        assert chunks_cold == 3            # 18 tokens = 8 + 8 + tail

        b = sched.submit(Request(prompt=prefix + tail_b,
                                 max_new_tokens=4))
        _drain(sched)
        assert eng.pool.prefix_hits == 1
        assert obs.counter("serve.kv.prefix_hits_total").value == 1
        # Hit: the 16 cached positions are referenced, not re-run —
        # prefill shrinks to ONE tail chunk.
        assert obs.counter("serve.prefill.chunks_total").value \
            == chunks_cold + 1
    finally:
        obs.end_run()
    stats = eng.compile_stats()
    assert stats["entries"] == stats["misses"] == \
        1 + len(PCFG.prefill_buckets)
    eng.pool.leak_check()

    cold = Engine(model, variables,
                  dataclasses.replace(PCFG, prefix_cache=False))
    sc = Scheduler(cold)
    b2 = sc.submit(Request(prompt=prefix + tail_b, max_new_tokens=4))
    _drain(sc)
    assert sched.results[b].tokens == sc.results[b2].tokens
    assert cold.pool.prefix_hits == 0 and len(cold.pool.trie) == 0


def test_cow_on_shared_block_write_with_live_donor(model_and_vars):
    """An exactly-block-aligned full-prefix hit must WRITE into its
    last shared block (the final prompt token re-runs to seed logits):
    that block is copied first (copy-on-write), the donor's cached
    copy stays intact — proven by a THIRD identical request hitting
    the cache again and still decoding identically — and the books
    balance."""
    model, variables = model_and_vars
    prompt = [(5 * i + 11) % 97 for i in range(12)]   # exactly 3 blocks
    eng = Engine(model, variables, PCFG)
    sched = Scheduler(eng)
    ref = _greedy_ref(model, variables, prompt, 6)
    a = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    _drain(sched)
    assert sched.results[a].tokens == ref
    assert eng.pool.cow_copies == 0
    # Aligned full hit: shared_len caps at n-1 inside the last cached
    # block -> prepare_write COWs it before the tail chunk runs.
    b = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    c = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    _drain(sched)
    assert eng.pool.prefix_hits == 2 and eng.pool.cow_copies >= 2
    assert sched.results[b].tokens == ref
    assert sched.results[c].tokens == ref
    eng.pool.leak_check()


# ------------------------------------------- stale-KV reuse invariant
_POISON = 1.0e3   # finite but logit-wrecking if a single stale
                  # position ever gets nonzero attention weight
                  # (NaN would ALSO poison legitimately-masked scores
                  # through the additive -inf mask — the layouts'
                  # guarantee is zero WEIGHT on stale positions, which
                  # only a finite sentinel tests honestly; the flash
                  # kernel path additionally never loads them)


def _poison_free_storage(eng):
    """Overwrite every cache position a retired request left behind
    (paged: all free blocks; dense: the whole pool — every slot is free
    after drain) with a huge sentinel. If ANY stale position were
    attendable before its new owner overwrites it, the sentinel would
    visibly skew the logits and the token-for-token reference
    comparison below would fail."""
    if eng.paged:
        idx = jnp.asarray(sorted(eng.pool._free_blocks), jnp.int32)
        eng.pool.caches = [
            {kv: leaf.at[idx].set(_POISON)
             for kv, leaf in layer.items()}
            for layer in eng.pool.caches]
    else:
        eng.pool.caches = [
            {kv: jnp.full_like(leaf, _POISON)
             for kv, leaf in layer.items()}
            for layer in eng.pool.caches]


@pytest.mark.parametrize("layout", ["paged", "dense"])
def test_stale_kv_never_attendable_after_rebind(model_and_vars, layout):
    """THE reuse invariant slots.py documents: a freed block (or slot
    row) rebound to a new request must never be attendable before that
    request overwrites it. Serve a request, retire it, poison all freed
    storage with NaN, then serve a different request through the same
    storage — its tokens must match a clean-engine reference exactly
    (any attention over stale positions would surface as a NaN logit
    burst and an ERROR retirement)."""
    model, variables = model_and_vars
    cfg = PCFG if layout == "paged" else DCFG
    if layout == "paged":
        # prefix_cache off: every block the first request bound is
        # genuinely FREED at retirement (no trie refs), so the poison
        # covers the exact storage the second request rebinds.
        cfg = dataclasses.replace(cfg, prefix_cache=False)
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    first = sched.submit(Request(
        prompt=[(7 * i + 1) % 97 for i in range(20)], max_new_tokens=8))
    _drain(sched)
    assert sched.results[first].finish_reason == "length"
    _poison_free_storage(eng)
    prompt2 = [9, 8, 7, 6, 5]
    second = sched.submit(Request(prompt=prompt2, max_new_tokens=8))
    _drain(sched)
    res = sched.results[second]
    assert res.finish_reason == "length", res.error
    assert res.tokens == _greedy_ref(model, variables, prompt2, 8)
    if layout == "paged":
        eng.pool.leak_check()


# ------------------------------------------------ occupancy + exhaustion
def test_paged_admits_more_residents_than_dense_at_equal_memory(
        model_and_vars):
    """The tentpole's occupancy claim at engine level: with the SAME
    device KV budget (96 token-positions), the dense layout caps at 2
    resident requests (2 slots x worst-case 48), while the paged pool
    runs 4 short requests concurrently — because blocks bind for
    tokens actually written, not for max_len."""
    model, variables = model_and_vars
    dense = Engine(model, variables, dataclasses.replace(
        DCFG, max_batch_size=2))                       # 2 * 48 = 96
    paged = Engine(model, variables, dataclasses.replace(
        PCFG, max_batch_size=4, kv_block_size=8,
        kv_num_blocks=13))                             # 12 * 8 = 96
    reqs = [Request(prompt=[3 + i, 1, 4, 1], max_new_tokens=8,
                    request_id=f"r{i}") for i in range(6)]
    peaks = {}
    for name, eng in (("dense", dense), ("paged", paged)):
        sched = Scheduler(eng)
        for r in reqs:
            sched.submit(dataclasses.replace(r))
        peak = 0
        for _ in range(400):
            if not sched.has_work():
                break
            sched.step()
            peak = max(peak, len(sched._live))
        assert not sched.has_work()
        assert all(sched.results[f"r{i}"].finish_reason == "length"
                   for i in range(6))
        peaks[name] = peak
    assert peaks["dense"] == 2
    assert peaks["paged"] == 4           # strictly more, equal memory
    paged.pool.leak_check()


def test_block_exhaustion_retires_victim_not_batch(model_and_vars):
    """Decode-time block exhaustion is REQUEST-SCOPED backpressure:
    with 5 usable blocks and two requests that each need 5, one row's
    lazy bind fails mid-decode -> that request retires with a typed
    'kv blocks exhausted' error (its blocks freed same-iteration), the
    survivor finishes its full budget, and nothing leaks."""
    model, variables = model_and_vars
    eng = Engine(model, variables, dataclasses.replace(
        PCFG, max_batch_size=2, kv_num_blocks=6, prefix_cache=False))
    sched = Scheduler(eng)
    a = sched.submit(Request(prompt=[1, 2, 3, 4], max_new_tokens=16,
                             request_id="a"))
    b = sched.submit(Request(prompt=[5, 6, 7, 8], max_new_tokens=16,
                             request_id="b"))
    _drain(sched)
    reasons = {sched.results[r].finish_reason for r in (a, b)}
    assert reasons == {"length", "error"}
    errored = next(r for r in (a, b)
                   if sched.results[r].finish_reason == "error")
    survivor = next(r for r in (a, b) if r != errored)
    assert "kv blocks exhausted" in sched.results[errored].error
    assert len(sched.results[survivor].tokens) == 16
    assert eng.pool.num_free == 2
    eng.pool.leak_check()
    # A request that could NEVER fit bounces at submit, holding nothing.
    with pytest.raises(ValueError, match="KV blocks"):
        sched.submit(Request(prompt=list(range(1, 30)),
                             max_new_tokens=17))


def test_lru_eviction_reclaims_cache_blocks(model_and_vars):
    """When the free list dries up, LRU trie-only blocks are evicted to
    serve new bindings (the cache is a best-effort accelerant, never a
    reservation); with kv_eviction='none' the same pressure surfaces
    as typed backpressure instead."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(PCFG, max_batch_size=1, kv_num_blocks=8)
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    p1 = [(3 * i + 2) % 97 for i in range(12)]       # 3 full blocks
    sched.submit(Request(prompt=p1, max_new_tokens=4))
    _drain(sched)
    assert len(eng.pool.trie) == 3
    # 7 usable blocks, 3 cached: a request needing 6 evicts from the
    # trie instead of failing.
    p2 = [(5 * i + 1) % 97 for i in range(20)]
    r = sched.submit(Request(prompt=p2, max_new_tokens=3))
    _drain(sched)
    assert sched.results[r].finish_reason == "length"
    assert len(eng.pool.trie) < 3 + 5    # eviction happened
    eng.pool.leak_check()

    none = Engine(model, variables, dataclasses.replace(
        cfg, kv_eviction="none"))
    sn = Scheduler(none)
    sn.submit(Request(prompt=p1, max_new_tokens=4))
    _drain(sn)
    r2 = sn.submit(Request(prompt=p2, max_new_tokens=3))
    # Admission sees available_blocks() without eviction, and with
    # NOTHING in flight no retirement can ever free the cache-pinned
    # blocks — waiting would livelock, so the head retires with a
    # typed error instead (never a hang, never a crash).
    sn.step()
    assert sn.queue_depth == 0
    assert sn.results[r2].finish_reason == "error"
    assert "kv blocks exhausted" in sn.results[r2].error
    none.pool.clear_prefix_cache()       # operator relief valve
    r3 = sn.submit(Request(prompt=p2, max_new_tokens=3))
    _drain(sn)
    assert sn.results[r3].finish_reason == "length"
    none.pool.leak_check()


def test_prefix_hit_falls_back_to_cold_prefill_in_tight_pool(
        model_and_vars):
    """Pathological tight pool: a fully-cached prompt's hit pins the
    very block its own copy-on-write then needs (free list empty, the
    only reclaimable block is the one the hit just referenced). The
    engine must fall back to a COLD prefill — releasing the hit's
    references makes the block evictable again — and serve the
    request, not retire it with a deterministic error a dense pool
    would never produce."""
    model, variables = model_and_vars
    # 3 usable blocks, blocks_per_slot 3 (max_len 12, bs 4).
    eng = Engine(model, variables, dataclasses.replace(
        PCFG, max_batch_size=2, max_len=12, kv_num_blocks=4))
    pool = eng.pool
    prompt_a = [11, 22, 33, 44]              # exactly one full block
    s0 = pool.alloc()
    eng.prefill(s0, prompt_a, max_new_tokens=4)
    pool.free(s0)                            # A cached: 1 trie-only block
    s0 = pool.alloc()
    # B (live): 7-token prompt binds the remaining 2 free blocks
    # (bucket-8 span) and stays resident.
    eng.prefill(s0, [60 + i for i in range(7)], max_new_tokens=1)
    assert pool.available_blocks() == 1      # A's cache block only
    s1 = pool.alloc()
    # The hit references A's block (ref 2 -> unevictable), then COW
    # finds no free and no reclaimable block: pre-fix this raised
    # KVBlocksExhausted out of prefill; the fallback must recover.
    eng.prefill(s1, prompt_a, max_new_tokens=4)
    # The old cache entry was evicted to feed the cold rebind, and the
    # rebuilt block was re-registered — one fresh entry, books balanced.
    assert len(pool.trie.match(prompt_a)) == 1
    pool.leak_check()
    # Retire B (as the scheduler would) so s1's decode growth has a
    # block to bind, and check the recovered row decodes normally.
    pool.free(s0)
    active = np.zeros((2,), bool)
    active[s1] = True
    tok, emitted = eng.step(active)
    assert emitted[s1] == 1
    pool.free(s1)
    pool.leak_check()


def test_eviction_skips_leaves_still_bound_by_live_requests(
        model_and_vars):
    """Exhaustion must only surface after every RECLAIMABLE block has
    been reclaimed: the LRU-oldest trie leaf may still be bound by a
    live prefix-hit request (ref > 1 — releasing the trie's ref frees
    nothing), and eviction has to skip it and take a younger ref-1
    leaf instead of destroying cache value and then failing anyway."""
    model, _ = model_and_vars
    pool = PagedSlotPool(model, capacity=3, max_len=16,
                         dtype=jnp.float32, block_size=4, num_blocks=5)
    t1, t2 = list(range(4)), [50 + i for i in range(4)]
    s1 = pool.alloc()                      # stays LIVE holding t1's block
    pool.bind_for_prompt(s1, t1)
    pool.prepare_write(s1, 0, 4)
    pool.register_prefix(s1, t1)           # trie ref -> block ref 2
    s2 = pool.alloc()                      # donor of the younger entry
    pool.bind_for_prompt(s2, t2)
    pool.prepare_write(s2, 0, 4)
    pool.register_prefix(s2, t2)
    pool.free(s2)                          # t2's block: trie-only, ref 1
    assert pool.available_blocks() == 3    # 2 free + 1 evictable
    # A request needing all 3: the LRU leaf (t1's, ref 2) must be
    # SKIPPED and t2's ref-1 leaf evicted — no KVBlocksExhausted.
    s3 = pool.alloc()
    pool.bind_for_prompt(s3, [70 + i for i in range(12)])
    pool.prepare_write(s3, 0, 12)
    assert len(pool.trie) == 1             # t1's entry survived
    assert pool.trie.match(t1) != []
    pool.free(s3)
    pool.free(s1)
    pool.leak_check()


def test_decode_binding_clamped_to_remaining_budget(model_and_vars):
    """A pool sized EXACTLY for a request's admission footprint must
    serve it to completion: with decode_horizon larger than the
    remaining budget, lazy binding only grows the write window by
    min(horizon, budget) — a row one token from finishing is never
    retired for blocks it would never write."""
    model, variables = model_and_vars
    # prompt 4 + max_new 4 = 8 tokens = exactly 2 blocks = the whole
    # usable pool; horizon 8 would naively demand [4, 12) = 3 blocks.
    eng = Engine(model, variables, dataclasses.replace(
        PCFG, max_batch_size=1, kv_num_blocks=3, prefix_cache=False,
        decode_horizon=8))
    sched = Scheduler(eng)
    rid = sched.submit(Request(prompt=[1, 2, 3, 4], max_new_tokens=4))
    _drain(sched)
    res = sched.results[rid]
    assert res.finish_reason == "length", res.error
    assert len(res.tokens) == 4
    eng.pool.leak_check()


# --------------------------------------------------- faults + chaos
def test_kv_bind_fault_injection_typed_backpressure(model_and_vars):
    """The serve.kv.bind fault point: an injected bind failure at
    admission retires ONLY that request (typed error, slot + blocks
    freed), and one injected mid-decode retires the victim with its
    pre-fault tokens — the engine never crashes and nothing leaks."""
    model, variables = model_and_vars
    eng = Engine(model, variables,
                 dataclasses.replace(PCFG, prefix_cache=False))
    sched = Scheduler(eng)
    try:
        faults.install(faults.FaultPlan.parse("serve.kv.bind:error@1"))
        bad = sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=4,
                                   request_id="bad"))
        ok = sched.submit(Request(prompt=[4, 5, 6], max_new_tokens=4,
                                  request_id="ok"))
        _drain(sched)
        assert sched.results[bad].finish_reason == "error"
        assert "injected" in sched.results[bad].error
        assert sched.results[ok].finish_reason == "length"
        assert len(sched.results[ok].tokens) == 4

        # Mid-decode: the 3rd bind of this request happens during lazy
        # decode growth (prefill spans 1 block, growth binds more).
        faults.install(faults.FaultPlan.parse("serve.kv.bind:error@3"))
        mid = sched.submit(Request(prompt=[7, 8, 9, 10],
                                   max_new_tokens=12,
                                   request_id="mid"))
        _drain(sched)
        res = sched.results[mid]
        assert res.finish_reason == "error"
        assert "kv blocks exhausted" in res.error
        assert 0 < len(res.tokens) < 12      # pre-fault tokens kept
    finally:
        faults.clear()
    assert eng.pool.num_free == PCFG.max_batch_size
    eng.pool.leak_check()


def test_chaos_paged_zero_block_leaks(model_and_vars, tmp_path):
    """The chaos acceptance on the paged pool at horizon 4: seeded
    prefill errors + NaN bursts + kv.bind failures over 16 requests
    with templated prompts (prefix hits + COW in play). EVERY request
    gets exactly one result, retired rows' block refs drop in the same
    iteration (zero slot leaks, zero block leaks — the ref-count books
    balance), the program set stays frozen, and the artifacts pass the
    pinned schema including the serve.kv.* instruments."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "chaos_paged")
    obs.start_run(run_dir, meta={"kind": "chaos_paged"})
    try:
        cfg = dataclasses.replace(PCFG, decode_horizon=4,
                                  queue_capacity=16)
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        faults.install(faults.FaultPlan.parse(
            "serve.prefill:error%0.08;serve.step.logits:nan%0.05;"
            "serve.kv.bind:error%0.03", seed=7))
        try:
            prefix = [(3 * i + 5) % 97 for i in range(8)]
            rids = []
            for i in range(16):
                prompt = (prefix + [i % 97, (2 * i) % 97]
                          if i % 2 else
                          [(11 * i + j) % 97 for j in range(6)])
                rids.append(sched.submit(Request(
                    prompt=prompt, max_new_tokens=6,
                    temperature=0.8 if i % 3 == 0 else 0.0,
                    top_k=10 if i % 3 == 0 else None, seed=i,
                    request_id=f"c{i}")))
            _drain(sched)
        finally:
            faults.clear()
        assert set(rids) <= set(sched.results)
        reasons = {sched.results[r].finish_reason for r in rids}
        assert reasons <= {"length", "error"}
        # Zero slot leaks, zero block leaks, frozen programs.
        assert eng.pool.num_free == cfg.max_batch_size
        eng.pool.leak_check()
        stats = eng.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(cfg.prefill_buckets)
        # The cache (trie refs) is the ONLY thing still holding blocks;
        # dropping it must empty the pool completely.
        eng.pool.clear_prefix_cache()
        eng.pool.leak_check()
        assert eng.pool.blocks_used == 0
    finally:
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert "serve.kv.prefix_hits_total" in summary["counters"]
    assert "serve.kv.cow_copies_total" in summary["counters"]
    assert "serve.kv.blocks_used" in summary["gauges"]
    # Dropping a kv instrument must FAIL the pinned schema.
    del summary["counters"]["serve.kv.prefix_hits_total"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.kv.prefix_hits_total" in e
               for e in check_run_dir(run_dir))
    from nezha_tpu.obs.report import render_report
    # (Report renders from the edited summary; the kv line keys on the
    # counters that remain — re-add and render.)
    summary["counters"]["serve.kv.prefix_hits_total"] = 1
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    report = render_report(run_dir)
    assert "kv:" in report and "prefix hits" in report


# ------------------------------------------------- config + bench + CLI
def test_serveconfig_kv_validation():
    with pytest.raises(ValueError, match="kv_layout"):
        ServeConfig(kv_layout="sparse")
    with pytest.raises(ValueError, match="kv_block_size"):
        ServeConfig(kv_block_size=0)
    with pytest.raises(ValueError, match="kv_num_blocks"):
        ServeConfig(kv_num_blocks=1)
    with pytest.raises(ValueError, match="kv_eviction"):
        ServeConfig(kv_eviction="fifo")


def test_serving_benchmark_shared_prefix_record(tmp_path):
    """benchmarks/serving.py --shared-prefix-frac: the templated-
    traffic record carries hit-rate, hit/miss TTFT, and the paged
    occupancy peaks, and the artifacts pass the pinned schema."""
    import serving as bench

    run_dir = str(tmp_path / "shared")
    rec = bench.run(bench.build_parser().parse_args(
        ["--requests", "10", "--concurrency", "3", "--max-new-tokens",
         "4", "--max-batch-size", "3", "--max-len", "48",
         "--max-prefill-len", "8", "--kv-block-size", "4",
         "--shared-prefix-frac", "0.8", "--shared-prefix-len", "16",
         "--run-dir", run_dir]))
    assert rec["finished"] == 10
    assert rec["kv"]["layout"] == "paged"
    assert rec["kv"]["prefix_hits"] > 0
    assert rec["kv"]["peak_resident_requests"] >= 1
    sp = rec["shared_prefix"]
    assert sp["len"] == 16 and sp["expected_hits"] > 0
    assert sp["prefix_hit_rate"] > 0
    assert sp["ttft_hit_s"]["p50"] > 0 and sp["ttft_miss_s"]["p50"] > 0
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []

    # The dense before/after knob still runs (and reports no hits).
    rec_d = bench.run(bench.build_parser().parse_args(
        ["--requests", "4", "--concurrency", "2", "--max-new-tokens",
         "2", "--max-batch-size", "2", "--max-len", "32",
         "--max-prefill-len", "8", "--kv-layout", "dense"]))
    assert rec_d["kv"]["layout"] == "dense"
    assert rec_d["kv"]["prefix_hits"] == 0


def test_nezha_bench_gates_against_committed_baseline(tmp_path):
    """The unified nezha-bench entry point: --update seeds a
    per-platform baseline, a re-run gates OK against it, and a cooked
    regression (baseline 10x better) fails the gate with exit 1 —
    without touching the other platform's slot."""
    from nezha_tpu.cli import bench as nb

    sb = str(tmp_path / "BENCH_serving.json")
    db = str(tmp_path / "BENCH_decode_attention.json")
    # Loose threshold: this test pins the GATE MECHANISM (seed /
    # compare / fail / per-platform isolation), not CPU timing
    # stability — interpret-mode microbench times swing well past the
    # default 30% under parallel test load, while the cooked 10x
    # regression below still trips an 80% bound.
    args = ["--quick", "--serving-baseline", sb,
            "--decode-baseline", db, "--requests", "4",
            "--horizons", "1,4", "--threshold", "0.8",
            "--platform", "cpu"]
    assert nb.main(args + ["--update"]) == 0
    base = json.load(open(sb))
    assert "cpu" in base["by_platform"]
    # The committed sweep's tokens/sec comes from the capture-free
    # pass, with the stitched trace block grafted in from the separate
    # captured pass (ISSUE 12): every horizon slot carries one.
    sweep = base["by_platform"]["cpu"]["closed_loop_horizon_sweep"]
    assert "capture-free" in sweep["trace_source"]
    for h_rec in sweep["by_horizon"].values():
        assert h_rec["trace"] and h_rec["trace"]["count"] > 0
    # A foreign platform slot must survive updates untouched.
    base["by_platform"]["tpu"] = {"closed_loop_horizon_sweep": {
        "by_horizon": {"1": {"tokens_per_sec": 123456.0}}}}
    json.dump(base, open(sb, "w"))
    rec = nb.run(nb.build_parser().parse_args(args))
    assert rec["ok"] and rec["platform"] == "cpu"
    assert rec["vs_baseline"]["serving"]  # gated something
    # Cook the cpu baseline 10x up -> regression detected, exit 1.
    base = json.load(open(sb))
    for h in base["by_platform"]["cpu"]["closed_loop_horizon_sweep"][
            "by_horizon"].values():
        h["tokens_per_sec"] *= 10
    json.dump(base, open(sb, "w"))
    assert nb.main(args) == 1
    base2 = json.load(open(sb))
    assert base2["by_platform"]["tpu"]["closed_loop_horizon_sweep"][
        "by_horizon"]["1"]["tokens_per_sec"] == 123456.0
