"""Sequence-parallel TRAINING correctness (VERDICT r2 #2): ring/Ulysses
attention gradients vs the dense reference, and the full dp x sp train step
vs single-device training. Forward-only parity lives in test_parallel.py."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nezha_tpu import data, ops, optim, parallel
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.parallel._compat import shard_map
from nezha_tpu.parallel.ring import ring_attention
from nezha_tpu.parallel.sequence_parallel import (
    make_sp_train_step,
    shard_lm_batch,
    ulysses_attention,
)
from nezha_tpu.train.loop import init_train_state, make_train_step


def _qkv(b=2, h=4, s=64, d=16, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, h, s, d)),
            jax.random.normal(kk, (b, h, s, d)),
            jax.random.normal(kv, (b, h, s, d)))


def _grad_parity(sp_attn_fn, causal, seed=0, h=4, dtype=None,
                 rtol=2e-4, atol=2e-5):
    """grad of a weighted-sum loss through the sharded attention must match
    the dense single-device attention's grad. ``dtype`` casts the q/k/v
    inputs (e.g. bf16, with loosened tolerances); the loss accumulates in
    fp32 either way."""
    mesh = parallel.make_mesh({"sp": 8})
    q, k, v = _qkv(seed=seed, h=h)
    if dtype is not None:
        q, k, v = (x.astype(dtype) for x in (q, k, v))
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)

    mapped = shard_map(sp_attn_fn, mesh=mesh,
                       in_specs=(P(None, None, "sp", None),) * 3,
                       out_specs=P(None, None, "sp", None))

    def sp_loss(q, k, v):
        return jnp.sum(mapped(q, k, v).astype(jnp.float32) * w)

    def ref_loss(q, k, v):
        mask = ops.causal_mask(q.shape[2], q.shape[2]) if causal else None
        return jnp.sum(
            ops.dot_product_attention(q, k, v, mask=mask).astype(jnp.float32)
            * w)

    g_sp = jax.jit(jax.grad(sp_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(g_sp, g_ref, "qkv"):
        af = np.asarray(a, np.float32)
        bf = np.asarray(b, np.float32)
        assert np.all(np.isfinite(af)), f"d{name} not finite"
        np.testing.assert_allclose(af, bf, rtol=rtol, atol=atol,
                                   err_msg=f"d{name} mismatch")


def test_ring_attention_grad_matches_full(devices8):
    """Backward through the ring (incl. the causal block-skip lax.cond,
    whose transpose nothing else exercises) vs dense attention."""
    for causal in (True, False):
        _grad_parity(
            partial(ring_attention, axis_name="sp", causal=causal), causal)


def test_ulysses_attention_grad_matches_full(devices8):
    _grad_parity(
        partial(ulysses_attention, axis_name="sp", causal=True),
        causal=True, h=8)


def test_ring_flash_grad_matches_full(devices8):
    """Flash-ring attention (per-hop Pallas flash blocks + ring-level
    custom VJP, parallel/ring.py:_ring_flash) vs dense reference —
    forward AND gradients, causal and full. ``use_flash=True`` forces the
    TPU path; the kernels run in interpret mode on CPU."""
    for causal in (True, False):
        _grad_parity(
            partial(ring_attention, axis_name="sp", causal=causal,
                    use_flash=True),
            causal=causal, seed=1)


def test_ring_flash_bf16_trains_finite(devices8):
    """bf16 inputs through the flash-ring (the training dtype on TPU): the
    fp32 merge/cast seams must produce finite gradients that track the
    dense bf16 reference within bf16 tolerance."""
    _grad_parity(
        partial(ring_attention, axis_name="sp", causal=True, use_flash=True),
        causal=True, dtype=jnp.bfloat16, rtol=0.1, atol=0.1)


def test_ulysses_flash_branch_grad_matches_full(devices8):
    """Execute the TPU flash-kernel branch of ulysses_attention (VERDICT r3
    weak #4): ``use_flash=True`` forces the Pallas path, which runs in
    interpret mode on CPU — all_to_all -> flash fwd/bwd custom VJP ->
    all_to_all, gradients and all, vs the dense reference."""
    _grad_parity(
        partial(ulysses_attention, axis_name="sp", causal=True,
                use_flash=True),
        causal=True, h=8)


def _tiny_gpt2(attn_impl="xla", sp_use_flash=None):
    return GPT2(GPT2Config(vocab_size=128, max_positions=64, num_layers=2,
                           num_heads=4, hidden_size=32, attn_impl=attn_impl,
                           sp_use_flash=sp_use_flash))


def _sp_vs_single(attn_impl, mesh_axes, sp_use_flash=None):
    """Run 3 identical steps single-device and sequence-parallel; params and
    losses must match."""
    mesh = parallel.make_mesh(mesh_axes)
    opt = optim.adamw(1e-3)
    rng = jax.random.PRNGKey(0)

    ref_model = _tiny_gpt2("xla")
    ref_state = init_train_state(ref_model, opt, rng)
    from nezha_tpu.models.gpt2 import lm_loss
    ref_step = make_train_step(ref_model, opt, lm_loss, donate=False)

    sp_model = _tiny_gpt2(attn_impl, sp_use_flash=sp_use_flash)
    sp_state = parallel.replicate(
        mesh, jax.tree_util.tree_map(jnp.copy, ref_state))
    sp_step = make_sp_train_step(sp_model, opt, mesh, donate=False)

    batches = data.synthetic_token_batches(8, seq_len=32, vocab_size=128)
    for _ in range(3):
        batch = next(batches)
        ref_state, ref_m = ref_step(ref_state, batch)
        sp_state, sp_m = sp_step(sp_state, shard_lm_batch(mesh, batch))
        np.testing.assert_allclose(float(sp_m["loss"]), float(ref_m["loss"]),
                                   rtol=1e-4, atol=1e-5)

    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(
                ref_state["variables"]["params"]),
            jax.tree_util.tree_leaves_with_path(
                sp_state["variables"]["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5,
                                   err_msg=jax.tree_util.keystr(ka))


def test_sp_train_step_ring_matches_single(devices8):
    """The dp x sp ring-attention training step (gradients and all) tracks
    single-device training step-for-step."""
    _sp_vs_single("ring", {"dp": 2, "sp": 4})


def test_sp_train_step_ulysses_matches_single(devices8):
    _sp_vs_single("ulysses", {"dp": 2, "sp": 4})


def test_sp_train_step_ring_flash_matches_single(devices8):
    """The FULL dp x sp training step with flash-ring attention (the TPU
    default, forced on via cfg.sp_use_flash so CI executes it in interpret
    mode) tracks single-device training step-for-step."""
    _sp_vs_single("ring", {"dp": 2, "sp": 4}, sp_use_flash=True)


def test_shard_lm_batch_rejects_ragged(devices8):
    mesh = parallel.make_mesh({"dp": 2, "sp": 4})
    import pytest
    with pytest.raises(ValueError, match="not divisible"):
        shard_lm_batch(mesh, {"tokens": np.zeros((4, 31), np.int32)})
