"""Cross-validation against the canonical torch GPT-2: load a randomly
initialized ``transformers.GPT2LMHeadModel``'s weights and require our
forward pass to reproduce its logits. This pins the numerical contract
(pre-norm blocks, tanh GELU, LN eps, attention scale, tied head) to the
published implementation, not just to our own tests."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=96, n_layer=3, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    return model


def test_logits_match_torch_reference(hf_model):
    from nezha_tpu.models.convert import gpt2_from_hf

    model, variables = gpt2_from_hf(hf_model)
    tokens = np.random.RandomState(0).randint(0, 128, (2, 17)).astype(np.int32)

    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens.astype(np.int64))).logits.numpy()

    ours, _ = model.apply(variables, tokens, training=False)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-4, rtol=2e-4)


def test_cached_generation_matches_torch_greedy(hf_model):
    from nezha_tpu.models.convert import gpt2_from_hf
    from nezha_tpu.models.generate import generate

    model, variables = gpt2_from_hf(hf_model)
    prompt = np.array([[11, 29, 3, 64]], np.int32)

    ref = hf_model.generate(
        torch.tensor(prompt.astype(np.int64)), max_new_tokens=10,
        do_sample=False, pad_token_id=0).numpy()

    import jax.numpy as jnp
    ours = np.asarray(generate(model, variables, prompt, max_new_tokens=10,
                               temperature=0.0, cache_dtype=jnp.float32))
    np.testing.assert_array_equal(ours, ref)


def test_roundtrip_export(hf_model):
    from nezha_tpu.models.convert import (
        gpt2_from_hf, gpt2_params_from_hf, gpt2_params_to_hf)

    model, variables = gpt2_from_hf(hf_model)
    exported = gpt2_params_to_hf(variables["params"], model.cfg.num_layers)
    re_imported = gpt2_params_from_hf(exported, model.cfg.num_layers)
    orig = gpt2_params_from_hf(hf_model.state_dict(), model.cfg.num_layers)

    import jax.tree_util as jtu
    leaves1 = jtu.tree_leaves(re_imported)
    leaves2 = jtu.tree_leaves(orig)
    assert len(leaves1) == len(leaves2)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.fixture(scope="module")
def hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=96, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,  # ratio 2
        max_position_embeddings=32, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, hidden_act="gelu")
    torch.manual_seed(1)
    model = transformers.BertForMaskedLM(cfg)
    model.eval()
    return model


def test_bert_logits_match_torch_reference(hf_bert):
    from nezha_tpu.models.convert import bert_from_hf

    model, variables = bert_from_hf(hf_bert)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 96, (2, 12)).astype(np.int32)
    segs = rng.randint(0, 2, (2, 12)).astype(np.int32)
    pad = np.ones((2, 12), bool)
    pad[1, 9:] = False  # a padded tail

    with torch.no_grad():
        ref = hf_bert(
            input_ids=torch.tensor(tokens.astype(np.int64)),
            token_type_ids=torch.tensor(segs.astype(np.int64)),
            attention_mask=torch.tensor(pad.astype(np.int64)),
        ).logits.numpy()

    ours, _ = model.apply(variables, {"tokens": tokens, "segment_ids": segs,
                                      "padding_mask": pad}, training=False)
    # Compare only non-pad positions: HF computes logits at pad slots too
    # but they attend differently and are never used.
    np.testing.assert_allclose(np.asarray(ours)[pad], ref[pad],
                               atol=3e-4, rtol=3e-4)


def test_bert_roundtrip_export(hf_bert):
    from nezha_tpu.models.convert import (
        bert_from_hf, bert_params_from_hf, bert_params_to_hf)

    model, variables = bert_from_hf(hf_bert)
    exported = bert_params_to_hf(variables["params"], model.cfg.num_layers,
                                 model.cfg.hidden_size)
    re_imported = bert_params_from_hf(exported, model.cfg.num_layers)
    orig = bert_params_from_hf(hf_bert.state_dict(), model.cfg.num_layers)

    import jax.tree_util as jtu
    leaves1 = jtu.tree_leaves(re_imported)
    leaves2 = jtu.tree_leaves(orig)
    assert len(leaves1) == len(leaves2)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # And HF itself accepts the exported dict (shape/key compatibility).
    import torch as _torch
    missing, unexpected = hf_bert.load_state_dict(
        {k: _torch.tensor(v) for k, v in exported.items()}, strict=False)
    assert not unexpected, unexpected
    # Nothing may be missing beyond torch-internal buffers.
    assert all("position_ids" in k for k in missing), missing
