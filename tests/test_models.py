"""Model-family tests: shapes, gradient flow, a few training steps on tiny
configs (SURVEY.md §2 models: ResNet-50, WRN-101, GPT-2, BERT)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import data, models, ops, optim
from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss
from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
from nezha_tpu.models.resnet import ResNet, resnet50, wide_resnet101
from nezha_tpu.train.loop import init_train_state, make_train_step


def tiny_resnet(**kw):
    return ResNet((1, 1), num_classes=10, **kw)


def tiny_gpt2(**kw):
    return GPT2(GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                           num_heads=2, hidden_size=32, **kw))


def tiny_bert(**kw):
    return Bert(BertConfig(vocab_size=128, max_positions=32, num_layers=2,
                           num_heads=2, hidden_size=32, **kw))


def test_resnet_forward_shapes():
    model = tiny_resnet()
    v = model.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 32, 32, 3))
    logits, states = model.apply(v, x, training=True)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # Every BatchNorm contributed a state update in training mode.
    assert "stem_bn" in states and "blocks0" in states


def test_s2d_stem_matches_conv7():
    """stem="s2d" is the same arithmetic as the 7x7/s2 conv, relaid out for
    the MXU (models/resnet.py:_space_to_depth_stem) — outputs must agree to
    fp32 summation-order tolerance, and gradients must flow to the SAME
    [7,7,3,64]-shaped parameter."""
    m7 = tiny_resnet(stem="conv7")
    ms = tiny_resnet(stem="s2d")
    v = m7.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y7, _ = m7.apply(v, x, training=False)
    ys, _ = ms.apply(v, x, training=False)
    np.testing.assert_allclose(np.asarray(y7), np.asarray(ys),
                               rtol=2e-5, atol=2e-5)

    def loss(params, model):
        vv = {**v, "params": params}
        out, _ = model.apply(vv, x, training=False)
        return (out.astype(jnp.float32) ** 2).sum()

    g7 = jax.grad(loss)(v["params"], m7)["stem_conv"]["w"]
    gs = jax.grad(loss)(v["params"], ms)["stem_conv"]["w"]
    assert gs.shape == (7, 7, 3, 64)
    np.testing.assert_allclose(np.asarray(g7), np.asarray(gs),
                               rtol=2e-4, atol=2e-4)


def test_s2d_stem_odd_input_falls_back():
    ms = tiny_resnet(stem="s2d")
    v = ms.init(jax.random.PRNGKey(0))
    logits, _ = ms.apply(v, jnp.ones((1, 31, 31, 3)), training=False)
    assert logits.shape == (1, 10)


def test_batchnorm_keeps_stats_fp32_normalizes_in_compute_dtype():
    """Stats are fp32 even under bf16 (SURVEY §0 config 5 mixed precision);
    the normalized output stays in the compute dtype with no fp32
    intermediate saved for backward (nn/layers.py BatchNorm)."""
    from nezha_tpu import nn
    from nezha_tpu.tensor import bf16_policy
    bn = nn.BatchNorm(8, policy=bf16_policy())
    v = bn.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 4, 4, 8), jnp.bfloat16)
    y, new_state = bn.apply(v, x, training=True)
    assert y.dtype == jnp.bfloat16
    assert new_state["mean"].dtype == jnp.float32
    assert new_state["var"].dtype == jnp.float32
    # Normalization is still correct: batch-normed output has ~0 mean/unit
    # var per channel (bf16 tolerance).
    yf = np.asarray(y, np.float32).reshape(-1, 8)
    assert np.abs(yf.mean(axis=0)).max() < 0.1
    assert np.abs(yf.std(axis=0) - 1.0).max() < 0.15


def test_resnet50_structure():
    model = resnet50()
    # 3+4+6+3 bottlenecks, ImageNet head.
    assert len(model.blocks) == 16
    assert model.head.out_features == 1000
    wrn = wide_resnet101(num_classes=5)
    assert len(wrn.blocks) == 33
    # Wide: first-stage bottleneck inner width is 128 (64*2).
    assert wrn.blocks[0].conv1.out_channels == 128
    # Output channels unchanged by widening.
    assert wrn.blocks[0].conv3.out_channels == 256


def test_resnet_zero_init_last_bn():
    model = tiny_resnet()
    v = model.init(jax.random.PRNGKey(0))
    np.testing.assert_array_equal(
        np.asarray(v["params"]["blocks0"]["bn3"]["scale"]), 0.0)


def test_resnet_trains():
    model = tiny_resnet()
    opt = optim.momentum(0.05)
    loss_fn = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"])
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, loss_fn)
    r = np.random.RandomState(0)
    losses = []
    for i in range(8):
        batch = {"image": r.rand(8, 32, 32, 3).astype(np.float32),
                 "label": (r.rand(8) * 10).astype(np.int32)}
        # Same 2 batches repeated -> memorization must drop the loss.
        batch = jax.tree_util.tree_map(jnp.asarray, batch) if i < 2 else batch
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()


def test_gpt2_forward_and_causality():
    model = tiny_gpt2()
    v = model.init(jax.random.PRNGKey(0))
    tokens = jnp.ones((2, 9), jnp.int32)
    logits, _ = model.apply(v, {"tokens": tokens}, training=False)
    assert logits.shape == (2, 8, 128)

    # Causality: changing a late token must not affect earlier logits.
    t1 = jnp.zeros((1, 8), jnp.int32)
    t2 = t1.at[0, 7].set(5)
    l1, _ = model.apply(v, t1)
    l2, _ = model.apply(v, t2)
    np.testing.assert_allclose(np.asarray(l1[:, :7]), np.asarray(l2[:, :7]),
                               atol=1e-5)
    assert not np.allclose(np.asarray(l1[:, 7]), np.asarray(l2[:, 7]))


def test_gpt2_124m_param_count():
    model = models.gpt2_124m()
    v = model.init(jax.random.PRNGKey(0))
    n = sum(x.size for x in jax.tree_util.tree_leaves(v["params"]))
    # GPT-2 124M: ~124.4M with tied head.
    assert 123e6 < n < 126e6, n


def test_gpt2_trains_on_repeated_batch():
    model = tiny_gpt2()
    opt = optim.adamw(1e-2, weight_decay=0.0)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss)
    batch = {"tokens": jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (4, 17)), jnp.int32)}
    first = last = None
    for i in range(15):
        state, m = step(state, batch)
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert last < first * 0.5, (first, last)


def test_bert_forward_padding_and_mlm():
    model = tiny_bert()
    v = model.init(jax.random.PRNGKey(0))
    batch = next(data.synthetic_mlm_batches(2, seq_len=16, vocab_size=128))
    logits, _ = model.apply(v, batch, training=False)
    assert logits.shape == (2, 16, 128)

    # Padding positions must not influence real positions.
    tokens = jnp.asarray(batch["tokens"])
    pm = jnp.ones((2, 16), bool).at[:, 8:].set(False)
    b1 = {"tokens": tokens, "padding_mask": pm}
    b2 = {"tokens": tokens.at[:, 12].set(7), "padding_mask": pm}
    l1, _ = model.apply(v, b1)
    l2, _ = model.apply(v, b2)
    np.testing.assert_allclose(np.asarray(l1[:, :8]), np.asarray(l2[:, :8]),
                               atol=1e-5)

    loss = mlm_loss(logits, batch)
    assert np.isfinite(float(loss))


def test_bert_zero1_trains(devices8):
    """The benchmark-config-4 path: BERT + ZeRO-1 on the 8-device mesh."""
    from nezha_tpu import parallel
    mesh = parallel.make_mesh({"dp": 8})
    model = tiny_bert()
    opt = optim.adamw(1e-3)
    variables = model.init(jax.random.PRNGKey(0))
    state = {
        "variables": parallel.replicate(mesh, variables),
        "opt_state": parallel.zero1_init_opt_state(opt, variables["params"], mesh),
        "rng": parallel.replicate(mesh, jax.random.PRNGKey(1)),
    }
    step = parallel.make_zero1_train_step(model, opt, mlm_loss, mesh,
                                          donate=False)
    batch = parallel.shard_batch(
        mesh, next(data.synthetic_mlm_batches(16, seq_len=16, vocab_size=128)))
    losses = []
    for _ in range(6):
        state, m = step(state, batch)  # same batch: memorization must help
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_gpt2_flash_attention_matches_xla():
    """attn_impl='flash' (Pallas, interpret on CPU) must match the composed
    XLA attention path on the same weights."""
    import jax
    import numpy as np

    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    kw = dict(vocab_size=64, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=64)
    m_xla = GPT2(GPT2Config(attn_impl="xla", **kw))
    m_flash = GPT2(GPT2Config(attn_impl="flash", **kw))
    variables = m_xla.init(jax.random.PRNGKey(0))
    tokens = jax.numpy.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 32)), jax.numpy.int32)
    out1, _ = m_xla.apply(variables, tokens, training=False)
    out2, _ = m_flash.apply(variables, tokens, training=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               atol=2e-5, rtol=2e-5)


def test_bert_flash_attention_matches_xla():
    """BertConfig.attn_impl='flash' (non-causal Pallas kernel, interpret on
    CPU) must match composed XLA attention on the same weights — forward
    AND one training step (loss + a couple of grads) — on full-length
    (no-padding) batches, the shape where 'auto' picks it on TPU."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss
    from nezha_tpu.train.loop import init_train_state, make_train_step

    kw = dict(vocab_size=64, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=64)
    m_xla = Bert(BertConfig(attn_impl="xla", **kw))
    m_flash = Bert(BertConfig(attn_impl="flash", **kw))
    variables = m_xla.init(jax.random.PRNGKey(0))
    r = np.random.RandomState(0)
    tokens = r.randint(0, 64, (2, 32)).astype(np.int32)
    labels = np.full_like(tokens, -100)
    sel = r.rand(2, 32) < 0.2
    labels[sel] = tokens[sel]
    batch = {"tokens": jnp.asarray(tokens), "labels": jnp.asarray(labels)}

    out1, _ = m_xla.apply(variables, batch, training=False)
    out2, _ = m_flash.apply(variables, batch, training=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               atol=2e-5, rtol=2e-5)

    opt = optim.adamw(1e-3)
    s1 = init_train_state(m_xla, opt, jax.random.PRNGKey(0))
    s2 = init_train_state(m_flash, opt, jax.random.PRNGKey(0))
    step1 = make_train_step(m_xla, opt, mlm_loss, donate=False)
    step2 = make_train_step(m_flash, opt, mlm_loss, donate=False)
    s1, me1 = step1(s1, batch)
    s2, me2 = step2(s2, batch)
    np.testing.assert_allclose(float(me1["loss"]), float(me2["loss"]),
                               rtol=1e-5)
    qkv1 = s1["variables"]["params"]["layers0"]["qkv"]["w"]
    qkv2 = s2["variables"]["params"]["layers0"]["qkv"]["w"]
    np.testing.assert_allclose(np.asarray(qkv1), np.asarray(qkv2),
                               atol=1e-5, rtol=1e-4)
    # A padding mask must refuse the flash impl loudly, never mis-attend.
    import pytest
    pm = jnp.ones((2, 32), bool)
    with pytest.raises(ValueError, match="padding"):
        m_flash.apply(variables, {"tokens": batch["tokens"],
                                  "padding_mask": pm}, training=False)


def test_bert_kv_lengths_flash_matches_xla_prefix_mask():
    """Right-padded batches via kv_lengths: the varlen flash path (interpret
    on CPU) and the composed-XLA prefix mask agree on loss AND on logits at
    valid positions (padded rows are unspecified and loss-masked)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss

    kw = dict(vocab_size=64, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=64)
    m_xla = Bert(BertConfig(attn_impl="xla", **kw))
    m_flash = Bert(BertConfig(attn_impl="flash", **kw))
    variables = m_xla.init(jax.random.PRNGKey(0))
    r = np.random.RandomState(1)
    tokens = r.randint(0, 64, (2, 32)).astype(np.int32)
    lengths = np.asarray([20, 32], np.int32)
    labels = np.full_like(tokens, -100)
    sel = r.rand(2, 32) < 0.3
    sel &= np.arange(32)[None, :] < lengths[:, None]  # only valid positions
    labels[sel] = tokens[sel]
    batch = {"tokens": jnp.asarray(tokens), "labels": jnp.asarray(labels),
             "kv_lengths": jnp.asarray(lengths)}

    out1, _ = m_xla.apply(variables, batch, training=False)
    out2, _ = m_flash.apply(variables, batch, training=False)
    valid = (np.arange(32)[None, :] < lengths[:, None])[..., None]
    np.testing.assert_allclose(np.where(valid, np.asarray(out1), 0),
                               np.where(valid, np.asarray(out2), 0),
                               atol=2e-5, rtol=2e-5)
    l1 = mlm_loss(out1, batch)
    l2 = mlm_loss(out2, batch)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    # Both length knobs at once is ambiguous — reject.
    import pytest
    with pytest.raises(ValueError, match="not both"):
        m_xla.apply(variables, {**batch,
                                "padding_mask": jnp.ones((2, 32), bool)})


def test_gpt2_pallas_ln_matches_xla():
    """ln_impl='pallas' (the fused LN kernel, interpret on CPU) must match
    the composed XLA layer norm through the whole model — forward AND one
    training step's gradients (the experiments/gpt2_tune.py variant must
    be exchangeable with the default before it can be flipped on-chip)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.train.loop import init_train_state, make_train_step

    kw = dict(vocab_size=64, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=64)
    m_xla = GPT2(GPT2Config(ln_impl="xla", **kw))
    m_pal = GPT2(GPT2Config(ln_impl="pallas", **kw))
    variables = m_xla.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 33)), jnp.int32)
    out1, _ = m_xla.apply(variables, tokens[:, :-1], training=False)
    out2, _ = m_pal.apply(variables, tokens[:, :-1], training=False)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               atol=2e-5, rtol=2e-5)

    opt = optim.adamw(1e-3)
    s1 = init_train_state(m_xla, opt, jax.random.PRNGKey(0))
    s2 = init_train_state(m_pal, opt, jax.random.PRNGKey(0))
    step1 = make_train_step(m_xla, opt, lm_loss, donate=False)
    step2 = make_train_step(m_pal, opt, lm_loss, donate=False)
    b = {"tokens": tokens}
    s1, me1 = step1(s1, b)
    s2, me2 = step2(s2, b)
    np.testing.assert_allclose(float(me1["loss"]), float(me2["loss"]),
                               rtol=2e-5)
    for (ka, a), (_, bb) in zip(
            jax.tree_util.tree_leaves_with_path(s1["variables"]["params"]),
            jax.tree_util.tree_leaves_with_path(s2["variables"]["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=jax.tree_util.keystr(ka))


def test_gpt2_remat_matches_exact_gradients():
    """cfg.remat changes memory scheduling, not math: loss and grads must
    match the non-remat model bit-for-bit-ish, including dropout rng replay
    inside the recomputed blocks."""
    def build(remat):
        return tiny_gpt2(dropout=0.1, remat=remat)

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 17)), jnp.int32)
    rng = jax.random.PRNGKey(3)

    def loss_grads(model):
        v = model.init(jax.random.PRNGKey(0))

        def loss(params):
            out, _ = model.apply({"params": params, "state": v["state"]},
                                 {"tokens": tokens}, training=True, rng=rng)
            return lm_loss(out, {"tokens": tokens})

        return jax.value_and_grad(loss)(v["params"])

    l0, g0 = loss_grads(build(False))
    l1, g1 = loss_grads(build(True))
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_gpt2_remat_decode_unaffected():
    """remat is training-only: the KV-cache decode path compiles and matches
    the non-remat model."""
    from nezha_tpu.models.generate import generate

    m0, m1 = tiny_gpt2(), tiny_gpt2(remat=True)
    v = m0.init(jax.random.PRNGKey(0))
    prompt = np.asarray([[5, 9, 2]], np.int32)
    a = generate(m0, v, prompt, max_new_tokens=6, temperature=0.0)
    b = generate(m1, v, prompt, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_s2d_stem_matches_conv7_under_bf16_policy():
    """The bench/CLI full-size path runs s2d under the bf16 policy — the
    relayout must stay equivalent at bf16 tolerances too."""
    from nezha_tpu.tensor import bf16_policy
    m7 = tiny_resnet(stem="conv7", policy=bf16_policy())
    ms = tiny_resnet(stem="s2d", policy=bf16_policy())
    v = m7.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
    y7, _ = m7.apply(v, x, training=False)
    ys, _ = ms.apply(v, x, training=False)
    np.testing.assert_allclose(np.asarray(y7, np.float32),
                               np.asarray(ys, np.float32),
                               rtol=5e-2, atol=5e-2)


def test_gpt2_scan_layers_matches_unrolled():
    """scan_layers is a params-layout + compile-strategy change, not math:
    same params (stacked) must give identical logits, loss, and gradients —
    including dropout rng replay (the per-layer h{i} key derivation is
    shared between layouts)."""
    from nezha_tpu.models.gpt2 import stack_layer_params, unstack_layer_params

    m0 = tiny_gpt2(dropout=0.1)
    m1 = tiny_gpt2(dropout=0.1, scan_layers=True)
    v0 = m0.init(jax.random.PRNGKey(0))
    p1 = stack_layer_params(v0["params"], m0.cfg.num_layers)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 17)), jnp.int32)
    rng = jax.random.PRNGKey(3)

    def loss_grads(model, params):
        def loss(p):
            out, _ = model.apply({"params": p, "state": {}},
                                 {"tokens": tokens}, training=True, rng=rng)
            return lm_loss(out, {"tokens": tokens})
        return jax.value_and_grad(loss)(params)

    l0, g0 = loss_grads(m0, v0["params"])
    l1, g1 = loss_grads(m1, p1)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    # Compare trunk grads layer-by-layer through the layout converter.
    g1u = unstack_layer_params(g1, m0.cfg.num_layers)
    flat0 = jax.tree_util.tree_leaves_with_path(g0)
    flat1 = dict(jax.tree_util.tree_leaves_with_path(g1u))
    # tree_leaves_with_path keys are comparable tuples; same structure.
    for path, a in flat0:
        b = flat1[path]
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_gpt2_scan_layers_roundtrip_and_init_layout():
    """A scan model's own init has the stacked layout; stack/unstack
    round-trips exactly."""
    from nezha_tpu.models.gpt2 import stack_layer_params, unstack_layer_params

    m1 = tiny_gpt2(scan_layers=True)
    v1 = m1.init(jax.random.PRNGKey(0))
    assert "h_scan" in v1["params"] and "h0" not in v1["params"]
    qkv_w = v1["params"]["h_scan"]["attn"]["qkv"]["w"]
    assert qkv_w.shape[0] == m1.cfg.num_layers
    rt = stack_layer_params(
        unstack_layer_params(v1["params"], m1.cfg.num_layers),
        m1.cfg.num_layers)
    for a, b in zip(jax.tree_util.tree_leaves(v1["params"]),
                    jax.tree_util.tree_leaves(rt)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gpt2_scan_layers_remat_matches():
    """remat composes with scan (jax.checkpoint around the scan body)."""
    from nezha_tpu.models.gpt2 import stack_layer_params

    m0 = tiny_gpt2()
    m1 = tiny_gpt2(scan_layers=True, remat=True)
    v0 = m0.init(jax.random.PRNGKey(0))
    p1 = stack_layer_params(v0["params"], m0.cfg.num_layers)
    tokens = jnp.asarray(
        np.random.RandomState(1).randint(0, 128, (2, 17)), jnp.int32)

    def loss(model, p):
        out, _ = model.apply({"params": p, "state": {}}, {"tokens": tokens},
                             training=True)
        return lm_loss(out, {"tokens": tokens})

    l0 = float(loss(m0, v0["params"]))
    l1 = float(loss(m1, p1))
    np.testing.assert_allclose(l0, l1, rtol=1e-6)


def test_gpt2_scan_layers_generate_matches():
    """The KV-cache decode path slices the stacked params per layer and
    emits h{i} cache states — greedy generate must match the unrolled
    layout token-for-token."""
    from nezha_tpu.models.generate import generate
    from nezha_tpu.models.gpt2 import stack_layer_params

    m0 = tiny_gpt2()
    m1 = tiny_gpt2(scan_layers=True)
    v0 = m0.init(jax.random.PRNGKey(0))
    v1 = {"params": stack_layer_params(v0["params"], m0.cfg.num_layers),
          "state": {}}
    prompt = np.asarray([[5, 9, 2]], np.int32)
    a = generate(m0, v0, prompt, max_new_tokens=6, temperature=0.0)
    b = generate(m1, v1, prompt, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gpt2_scan_layers_rejects_moe():
    with pytest.raises(ValueError, match="moe"):
        tiny_gpt2(scan_layers=True, moe_experts=4)


def test_bert_scan_layers_matches_unrolled():
    """BERT's scan encoder: same stacked params -> identical loss and
    grads vs the unrolled encoder, incl. dropout key replay and the
    kv_lengths broadcast input."""
    from nezha_tpu.nn.module import stack_prefixed_params

    m0 = tiny_bert(dropout=0.1, fused_loss_chunk=-1)
    m1 = tiny_bert(dropout=0.1, fused_loss_chunk=-1, scan_layers=True)
    v0 = m0.init(jax.random.PRNGKey(0))
    p1 = stack_prefixed_params(v0["params"], "layers", m0.cfg.num_layers,
                               "layers_scan")
    rng = jax.random.PRNGKey(3)
    rs = np.random.RandomState(0)
    batch = {"tokens": jnp.asarray(rs.randint(0, 128, (2, 16)), jnp.int32),
             "labels": jnp.asarray(
                 np.where(rs.rand(2, 16) < 0.3,
                          rs.randint(0, 128, (2, 16)), -100), jnp.int32),
             "kv_lengths": jnp.asarray([12, 16], jnp.int32)}

    def loss_grads(model, params):
        def loss(p):
            out, _ = model.apply({"params": p, "state": {}}, batch,
                                 training=True, rng=rng)
            return mlm_loss(out, batch)
        return jax.value_and_grad(loss)(params)

    l0, g0 = loss_grads(m0, v0["params"])
    l1, g1 = loss_grads(m1, p1)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    from nezha_tpu.nn.module import unstack_prefixed_params
    g1u = unstack_prefixed_params(g1, "layers", m0.cfg.num_layers,
                                  "layers_scan")
    flat1 = dict(jax.tree_util.tree_leaves_with_path(g1u))
    for path, a in jax.tree_util.tree_leaves_with_path(g0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(flat1[path]),
                                   rtol=1e-5, atol=1e-6)


def test_resnet_remat_matches_exact_gradients():
    """ResNet remat (per-bottleneck jax.checkpoint) changes memory
    scheduling, not math: loss, grads, AND BatchNorm running-stat updates
    match the non-remat model."""
    def build(remat):
        return ResNet((1, 1), num_classes=10, remat=remat)

    rs = np.random.RandomState(0)
    batch = {"image": jnp.asarray(rs.rand(2, 32, 32, 3).astype(np.float32)),
             "label": jnp.asarray(rs.randint(0, 10, 2), jnp.int32)}

    def loss_grads(model):
        v = model.init(jax.random.PRNGKey(0))

        def loss(params):
            logits, st = model.apply({"params": params,
                                      "state": v["state"]},
                                     batch, training=True)
            l = ops.softmax_cross_entropy_with_integer_labels(
                logits, batch["label"]).mean()
            return l, st

        (l, st), g = jax.value_and_grad(loss, has_aux=True)(v["params"])
        return l, g, st

    l0, g0, st0 = loss_grads(build(False))
    l1, g1, st1 = loss_grads(build(True))
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(st0),
                    jax.tree_util.tree_leaves(st1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_scan_layers_init_matches_unrolled_init():
    """Same seed -> the scan layout initializes to EXACTLY the stacked
    unrolled init, for both models (the _init_with_parent_rng contract:
    layer keys derive from the model's rng, not the stack child's name)."""
    from nezha_tpu.nn.module import stack_prefixed_params

    for build, prefix, key in (
            (tiny_gpt2, "h", "h_scan"),
            (lambda **kw: tiny_bert(**kw), "layers", "layers_scan")):
        m0 = build()
        m1 = build(scan_layers=True)
        v0 = m0.init(jax.random.PRNGKey(7))
        v1 = m1.init(jax.random.PRNGKey(7))
        expect = stack_prefixed_params(v0["params"], prefix,
                                       m0.cfg.num_layers, key)
        flat1 = dict(jax.tree_util.tree_leaves_with_path(v1["params"]))
        for path, a in jax.tree_util.tree_leaves_with_path(expect):
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(flat1[path]), err_msg=str(path))


def test_bert_pallas_ln_matches_xla():
    """BertConfig.ln_impl='pallas' routes all four LN sites through the
    fused kernel (interpret mode on CPU) with unchanged numerics."""
    m0 = tiny_bert(fused_loss_chunk=-1)
    m1 = tiny_bert(fused_loss_chunk=-1, ln_impl="pallas")
    v = m0.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    batch = {"tokens": jnp.asarray(rs.randint(0, 128, (2, 16)), jnp.int32),
             "labels": jnp.asarray(
                 np.where(rs.rand(2, 16) < 0.3,
                          rs.randint(0, 128, (2, 16)), -100), jnp.int32)}

    def loss(model, p):
        out, _ = model.apply({"params": p, "state": {}}, batch,
                             training=True)
        return mlm_loss(out, batch)

    l0 = float(loss(m0, v["params"]))
    l1 = float(loss(m1, v["params"]))
    # On CPU the pallas impl falls back to XLA composition, so this pins
    # the wiring (same params tree, same numerics), not the kernel.
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
