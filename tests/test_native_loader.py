"""Native C++ loader tests: IDX parsing, epoch coverage, shuffling,
determinism, token windows, and end-to-end flow into the Prefetcher."""

import struct

import numpy as np
import pytest

from nezha_tpu.runtime.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime library not buildable")

from nezha_tpu.data.native import (  # noqa: E402
    MnistLoader, NativeLoaderError, TokenLoader)


def _write_idx(tmp_path, n=64, rows=4, cols=4, seed=0):
    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, size=(n, rows, cols)).astype(np.uint8)
    labels = (np.arange(n) % 10).astype(np.uint8)
    img_path = tmp_path / "images-idx3-ubyte"
    lbl_path = tmp_path / "labels-idx1-ubyte"
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path, images, labels


def test_mnist_shapes_and_normalization(tmp_path):
    img, lbl, images, labels = _write_idx(tmp_path)
    with MnistLoader(img, lbl, batch_size=8, epochs=1) as ld:
        assert ld.num_examples == 64 and ld.example_dim == 16
        batch = next(iter(ld))
    assert batch["image"].shape == (8, 16)
    assert batch["image"].dtype == np.float32
    assert batch["label"].shape == (8,)
    assert 0.0 <= batch["image"].min() and batch["image"].max() <= 1.0


def test_mnist_one_epoch_covers_every_example_once(tmp_path):
    img, lbl, images, labels = _write_idx(tmp_path, n=64)
    with MnistLoader(img, lbl, batch_size=8, epochs=1, num_workers=3) as ld:
        batches = list(ld)
    assert len(batches) == 8
    # Reconstruct which source row each served example was (pixels are
    # random enough to identify rows uniquely).
    flat = (images.reshape(64, -1).astype(np.float32) / 255.0)
    seen = []
    for b in batches:
        for row, y in zip(b["image"], b["label"]):
            idx = int(np.argmin(np.abs(flat - row).sum(axis=1)))
            assert np.allclose(flat[idx], row, atol=1e-6)
            assert labels[idx] == y
            seen.append(idx)
    assert sorted(seen) == list(range(64))


def test_mnist_batch_larger_than_dataset_rejected(tmp_path):
    # batch > n would make nbatch == 0; with infinite epochs the workers
    # would spin forever and close() would hang in join.
    img, lbl, _, _ = _write_idx(tmp_path, n=16)
    with pytest.raises(NativeLoaderError, match="batch size must be in"):
        MnistLoader(img, lbl, batch_size=32)


def test_mnist_shuffles_between_epochs(tmp_path):
    img, lbl, _, _ = _write_idx(tmp_path, n=64)
    with MnistLoader(img, lbl, batch_size=64, epochs=2, num_workers=1) as ld:
        it = iter(ld)
        e1 = next(it)["label"]
        e2 = next(it)["label"]
    assert not np.array_equal(e1, e2)  # different permutations
    assert sorted(e1) == sorted(e2)    # same multiset


def test_mnist_deterministic_given_seed(tmp_path):
    img, lbl, _, _ = _write_idx(tmp_path)
    def first_labels(seed):
        with MnistLoader(img, lbl, batch_size=16, seed=seed, epochs=1,
                         num_workers=1) as ld:
            return next(iter(ld))["label"].copy()
    assert np.array_equal(first_labels(7), first_labels(7))
    assert not np.array_equal(first_labels(7), first_labels(8))


def test_mnist_bad_magic_raises(tmp_path):
    p = tmp_path / "bad"
    p.write_bytes(b"\x00\x00\x00\x00" + b"\x00" * 32)
    with pytest.raises(NativeLoaderError):
        MnistLoader(p, p, batch_size=4)


def test_tokens_windows_match_source(tmp_path):
    toks = np.arange(1000, dtype=np.uint16)
    p = tmp_path / "tokens.bin"
    p.write_bytes(toks.tobytes())
    with TokenLoader(p, seq_len=16, batch_size=4, dtype=np.uint16) as ld:
        assert ld.num_tokens == 1000
        batch = next(iter(ld))
    assert batch["tokens"].shape == (4, 17)
    # Consecutive source: every window must be consecutive integers.
    for row in batch["tokens"]:
        assert np.array_equal(row, np.arange(row[0], row[0] + 17))


def test_tokens_int32_dtype(tmp_path):
    toks = np.arange(500, dtype=np.int32) * 3
    p = tmp_path / "tokens32.bin"
    p.write_bytes(toks.tobytes())
    with TokenLoader(p, seq_len=8, batch_size=2, dtype=np.int32) as ld:
        batch = next(iter(ld))
    for row in batch["tokens"]:
        assert np.array_equal(row, np.arange(row[0] // 3,
                                             row[0] // 3 + 9) * 3)


def test_tokens_too_short_raises(tmp_path):
    p = tmp_path / "short.bin"
    p.write_bytes(np.arange(4, dtype=np.uint16).tobytes())
    with pytest.raises(NativeLoaderError):
        TokenLoader(p, seq_len=16, batch_size=1)


def test_native_loader_through_prefetcher(tmp_path):
    """End-to-end: C++ loader -> Prefetcher -> device arrays."""
    import jax

    from nezha_tpu.runtime.prefetch import Prefetcher

    img, lbl, _, _ = _write_idx(tmp_path, n=32)
    with MnistLoader(img, lbl, batch_size=8, epochs=1) as ld:
        pf = Prefetcher(iter(ld), depth=2)
        batches = list(pf)
    assert len(batches) == 4
    assert all(isinstance(b["image"], jax.Array) for b in batches)


def test_mnist_truncated_labels_rejected(tmp_path):
    """Header says n examples but label body is shorter: must error, not
    read out of bounds."""
    img, lbl, _, _ = _write_idx(tmp_path, n=64)
    raw = lbl.read_bytes()
    lbl.write_bytes(raw[:8 + 10])  # keep header, truncate body
    with pytest.raises(NativeLoaderError):
        MnistLoader(img, lbl, batch_size=8)


def _write_records(tmp_path, n=32, h=12, w=12, c=3, seed=0):
    from nezha_tpu.data.native import write_image_records
    rng = np.random.RandomState(seed)
    images = rng.randint(0, 256, size=(n, h, w, c)).astype(np.uint8)
    labels = (np.arange(n) % 7).astype(np.int32)
    p = tmp_path / "data.nzr"
    write_image_records(p, images, labels)
    return p, images, labels


def test_records_shapes_and_center_crop(tmp_path):
    from nezha_tpu.data.native import ImageRecordLoader
    p, images, labels = _write_records(tmp_path)
    with ImageRecordLoader(p, batch_size=8, crop=8, epochs=1,
                           train_augment=False, num_workers=1) as ld:
        assert ld.num_examples == 32 and ld.shape == (8, 8, 3)
        batch = next(iter(ld))
    assert batch["image"].shape == (8, 8, 8, 3)
    assert batch["image"].dtype == np.float32
    # Center crop: each served image must equal the [2:10, 2:10] window of
    # its source (identified by label order is shuffled — match by content).
    flat_src = images[:, 2:10, 2:10, :].reshape(32, -1).astype(np.float32) / 255.0
    for img, y in zip(batch["image"], batch["label"]):
        row = img.reshape(-1)
        idx = int(np.argmin(np.abs(flat_src - row).sum(axis=1)))
        assert np.allclose(flat_src[idx], row, atol=1e-6)
        assert labels[idx] == y


def test_records_epoch_coverage(tmp_path):
    from nezha_tpu.data.native import ImageRecordLoader
    p, _, _ = _write_records(tmp_path, n=32)
    with ImageRecordLoader(p, batch_size=8, epochs=1, num_workers=3,
                           train_augment=False) as ld:
        batches = list(ld)
    assert len(batches) == 4
    served = np.concatenate([b["label"] for b in batches])
    assert sorted(served) == sorted((np.arange(32) % 7))


def test_records_augment_crops_within_source(tmp_path):
    """Random crop + flip: every served crop must appear somewhere in its
    source image (possibly mirrored), and augmented epochs must differ."""
    from nezha_tpu.data.native import ImageRecordLoader
    p, images, labels = _write_records(tmp_path, n=8, h=10, w=10)
    with ImageRecordLoader(p, batch_size=8, crop=6, epochs=2,
                           train_augment=True, num_workers=1, seed=3) as ld:
        it = iter(ld)
        b1, b2 = next(it), next(it)
    assert not np.array_equal(b1["image"], b2["image"])
    src = images.astype(np.float32) / 255.0
    for img, y in zip(b1["image"], b1["label"]):
        found = False
        for i in np.flatnonzero(labels == y):
            for cand in (src[i], src[i, :, ::-1]):
                for dy in range(5):
                    for dx in range(5):
                        if np.allclose(cand[dy:dy+6, dx:dx+6], img,
                                       atol=1e-6):
                            found = True
        assert found, "served crop not found in any source window"


def test_records_bad_magic(tmp_path):
    from nezha_tpu.data.native import ImageRecordLoader
    p = tmp_path / "bad.nzr"
    p.write_bytes(b"XXXX" + b"\x00" * 64)
    with pytest.raises(NativeLoaderError):
        ImageRecordLoader(p, batch_size=4)


def test_records_train_resnet_smoke(tmp_path):
    """Record loader -> ResNet train step on CPU: loss is finite."""
    import jax

    from nezha_tpu import optim, ops
    from nezha_tpu.models.resnet import ResNet
    from nezha_tpu.train.loop import init_train_state, make_train_step

    from nezha_tpu.data.native import ImageRecordLoader
    p, _, _ = _write_records(tmp_path, n=16, h=36, w=36)

    def loss_fn(logits, batch):
        return ops.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"])

    model = ResNet(stage_sizes=(1, 1, 1, 1), num_classes=7)
    opt = optim.sgd(1e-2)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, loss_fn)
    with ImageRecordLoader(p, batch_size=8, crop=32, epochs=1,
                           num_workers=2) as ld:
        for batch in ld:
            state, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))


def test_records_batch_larger_than_dataset_rejected(tmp_path):
    from nezha_tpu.data.native import ImageRecordLoader
    p, _, _ = _write_records(tmp_path, n=8)
    with pytest.raises(NativeLoaderError, match="batch"):
        ImageRecordLoader(p, batch_size=64)


def test_records_sharding_partitions_each_epoch(tmp_path):
    """Multi-host sharding: two shards with the same seed consume disjoint
    halves of the epoch whose union is every record exactly once."""
    from nezha_tpu.data.native import ImageRecordLoader, write_image_records
    rng = np.random.RandomState(0)
    n = 32
    p = str(tmp_path / "r.nzr")
    write_image_records(p, rng.randint(0, 256, (n, 6, 6, 3), dtype=np.uint8),
                        np.arange(n))  # unique labels identify records
    served = {}
    for idx in range(2):
        with ImageRecordLoader(p, batch_size=4, epochs=1, num_workers=2,
                               train_augment=False, seed=7,
                               shard_index=idx, shard_count=2) as ld:
            served[idx] = np.concatenate([b["label"] for b in ld])
    assert len(served[0]) == len(served[1]) == n // 2
    assert not set(served[0]) & set(served[1])  # disjoint
    assert sorted(np.concatenate([served[0], served[1]])) == list(range(n))


def test_records_sharding_rejects_starved_shard(tmp_path):
    from nezha_tpu.data.native import (ImageRecordLoader, NativeLoaderError,
                                       write_image_records)
    rng = np.random.RandomState(0)
    p = str(tmp_path / "r.nzr")
    write_image_records(p, rng.randint(0, 256, (8, 4, 4, 3), dtype=np.uint8),
                        np.arange(8))
    # 2 batches per epoch cannot feed 4 shards.
    with pytest.raises(NativeLoaderError, match="shard_count"):
        ImageRecordLoader(p, batch_size=4, shard_index=0, shard_count=4)
    with pytest.raises(NativeLoaderError, match="shard_index"):
        ImageRecordLoader(p, batch_size=4, shard_index=2, shard_count=2)


def test_tokens_sharding_decorrelates_streams(tmp_path):
    from nezha_tpu.data.native import TokenLoader
    toks = np.arange(4096, dtype=np.uint16)
    p = str(tmp_path / "t.bin")
    toks.tofile(p)
    outs = []
    for idx in range(2):
        with TokenLoader(p, seq_len=16, batch_size=4, seed=3,
                         num_workers=1, shard_index=idx,
                         shard_count=2) as ld:
            outs.append(next(iter(ld))["tokens"].copy())
    assert not np.array_equal(outs[0], outs[1])  # different window streams


def test_records_uneven_shards_serve_equal_counts(tmp_path):
    """nbatch not divisible by shard_count: every shard serves exactly
    floor(nbatch/shard_count) batches per epoch (ragged tail dropped), so
    lockstep multi-host consumers can never deadlock on a short shard."""
    from nezha_tpu.data.native import ImageRecordLoader, write_image_records
    rng = np.random.RandomState(0)
    n, batch, shards = 40, 4, 3  # 10 batches -> 3 per shard, 1 dropped
    p = str(tmp_path / "r.nzr")
    write_image_records(p, rng.randint(0, 256, (n, 5, 5, 3), dtype=np.uint8),
                        np.arange(n))
    counts, seen = [], []
    for idx in range(shards):
        with ImageRecordLoader(p, batch_size=batch, epochs=1, num_workers=2,
                               train_augment=False, seed=5,
                               shard_index=idx, shard_count=shards) as ld:
            labels = [b["label"] for b in ld]
        counts.append(len(labels))
        seen.extend(np.concatenate(labels).tolist())
    assert counts == [3, 3, 3]  # floor(10/3) each, no ragged shard
    assert len(seen) == len(set(seen)) == 36  # disjoint, 4 records dropped


def test_mlm_masking_recipe():
    """data.mlm: ~mask_rate positions selected; of those ~80% mask_token,
    ~10% random, ~10% unchanged; labels carry originals exactly at
    selections; off-selection labels are -100 and tokens untouched."""
    import numpy as np

    from nezha_tpu.data.mlm import mlm_batches_from_tokens

    rng = np.random.RandomState(0)
    orig = rng.randint(0, 200, (64, 257)).astype(np.int32)  # [B, S+1]
    out = next(mlm_batches_from_tokens([{"tokens": orig}], vocab_size=256,
                                       mask_token=255, seed=1,
                                       drop_last_column=True))
    tokens, labels = out["tokens"], out["labels"]
    assert tokens.shape == labels.shape == (64, 256)
    base = orig[:, :-1]
    sel = labels != -100
    rate = sel.mean()
    assert 0.10 < rate < 0.20, rate
    np.testing.assert_array_equal(labels[sel], base[sel])
    np.testing.assert_array_equal(tokens[~sel], base[~sel])
    masked = (tokens == 255) & sel
    changed = sel & (tokens != base) & ~masked
    kept = sel & (tokens == base)
    n = sel.sum()
    assert 0.7 < masked.sum() / n < 0.9
    assert changed.sum() / n < 0.2
    assert kept.sum() / n < 0.2
    # Dynamic: a second pass re-rolls the selection.
    out2 = next(mlm_batches_from_tokens([{"tokens": orig}], vocab_size=256,
                                        mask_token=255, seed=2,
                                        drop_last_column=True))
    assert (out2["labels"] != labels).any()


def test_mlm_wrapper_rejects_bad_args():
    import numpy as np
    import pytest

    from nezha_tpu.data.mlm import mlm_batches_from_tokens

    toks = [{"tokens": np.zeros((2, 8), np.int32)}]
    with pytest.raises(ValueError, match="mask_rate"):
        next(mlm_batches_from_tokens(toks, 256, mask_rate=0.0))
    with pytest.raises(ValueError, match="outside vocab"):
        next(mlm_batches_from_tokens(toks, 256, mask_token=256))
    big = [{"tokens": np.full((2, 8), 600, np.int32)}]
    with pytest.raises(ValueError, match="outside"):
        next(mlm_batches_from_tokens(big, 256))
    neg = [{"tokens": np.full((2, 8), -3, np.int32)}]
    with pytest.raises(ValueError, match="outside"):
        next(mlm_batches_from_tokens(neg, 256))
