"""`nezha-generate` CLI: checkpoint restore + KV-cache decode end-to-end."""

import json

import numpy as np
import pytest

from nezha_tpu.cli.generate import build_parser, run as gen_run
from nezha_tpu.cli.train import build_parser as train_parser, run as train_run


def _gen(argv):
    return gen_run(build_parser().parse_args(argv))


def test_generate_from_trained_checkpoint(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "3",
         "--batch-size", "8", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "8",
                "--temperature", "0"])
    assert out["prompt_len"] == 3
    assert len(out["tokens"]) == 8
    assert all(0 <= t < 512 for t in out["tokens"])
    assert "restored step 3" in capsys.readouterr().err
    # Greedy decode from the same checkpoint is deterministic.
    again = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                  "--prompt-tokens", "5,17,3", "--max-new-tokens", "8",
                  "--temperature", "0"])
    assert again["tokens"] == out["tokens"]


def test_generate_from_graph_engine_checkpoint(tmp_path, capsys):
    """A GPT-2 trained with --engine graph checkpoints the IR trainer's
    {"params","mu","nu","step"} layout; nezha-generate must read it (the
    params are module-layout, so decode works unchanged)."""
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "3",
         "--batch-size", "8", "--engine", "graph", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "6",
                "--temperature", "0"])
    assert out["prompt_len"] == 3
    assert len(out["tokens"]) == 6
    assert "graph-engine layout" in capsys.readouterr().err

    # nezha-export reads the same layout (HF-keyed npz out).
    from nezha_tpu.cli.export import build_parser as ep, run as erun
    dest = str(tmp_path / "hf.npz")
    summary = erun(ep().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny",
         "--ckpt-dir", ck, "--out", dest, "--format", "npz"]))
    assert summary["keys"] > 0
    import numpy as _np
    assert any("wte" in k for k in _np.load(dest).files)


def test_generate_random_init_and_prompt_file(tmp_path):
    toks = np.asarray([1, 2, 3, 4], np.uint16)
    pf = str(tmp_path / "p.bin")
    toks.tofile(pf)
    out = _gen(["--random-init", "--model-preset", "tiny",
                "--prompt-file", pf, "--max-new-tokens", "4",
                "--temperature", "0.7", "--top-k", "5", "--seed", "3"])
    assert out["prompt_len"] == 4 and len(out["tokens"]) == 4


def test_generate_rejects_bad_inputs(tmp_path):
    with pytest.raises(SystemExit, match="exactly one of"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--max-new-tokens", "4"])
    with pytest.raises(SystemExit, match="comma-separated"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1,x2"])
    with pytest.raises(SystemExit, match=r"in \[0, 512\)"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "9999"])
    with pytest.raises(SystemExit, match="exceeds max_positions"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1,2", "--max-new-tokens", "200"])
    with pytest.raises(SystemExit, match="no checkpoint"):
        _gen(["--ckpt-dir", str(tmp_path / "none"), "--model-preset", "tiny",
              "--prompt-tokens", "1"])


def test_generate_from_hf_weights(tmp_path):
    transformers = pytest.importorskip("transformers")
    cfg = transformers.GPT2Config(vocab_size=128, n_positions=32, n_embd=32,
                                  n_layer=2, n_head=2)
    hf = transformers.GPT2LMHeadModel(cfg)
    hf.save_pretrained(tmp_path / "hf")
    out = _gen(["--hf-dir", str(tmp_path / "hf"),
                "--prompt-tokens", "5,9", "--max-new-tokens", "6",
                "--temperature", "0"])
    assert len(out["tokens"]) == 6
    assert all(0 <= t < 128 for t in out["tokens"])


def test_generate_text_prompt_byte_level(tmp_path):
    """--prompt encodes bytes (the data/pack.py training encoding) and the
    output decodes back to text."""
    out = _gen(["--random-init", "--model-preset", "tiny",
                "--prompt", "hi", "--max-new-tokens", "5",
                "--temperature", "0"])
    assert out["prompt_len"] == 2
    assert isinstance(out["text"], str)
    with pytest.raises(SystemExit, match="exactly one of"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt", "hi", "--prompt-tokens", "1"])
    with pytest.raises(SystemExit, match="empty"):
        _gen(["--random-init", "--model-preset", "tiny", "--prompt", ""])


def test_export_gpt2_npz_and_torch(tmp_path, devices8):
    """nezha-export converts a trained checkpoint to HF-keyed weights; the
    torch format loads straight into GPT2LMHeadModel."""
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run

    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "2",
         "--batch-size", "8", "--ckpt-dir", ck]))

    out = str(tmp_path / "w.npz")
    res = export_run(export_parser().parse_args(
        ["--config", "gpt2_124m", "--ckpt-dir", ck, "--model-preset",
         "tiny", "--out", out]))
    z = np.load(out)
    assert res["keys"] == len(z.files)
    np.testing.assert_array_equal(z["lm_head.weight"],
                                  z["transformer.wte.weight"])  # tied

    transformers = pytest.importorskip("transformers")
    import torch
    outb = str(tmp_path / "w.bin")
    export_run(export_parser().parse_args(
        ["--config", "gpt2_124m", "--ckpt-dir", ck, "--model-preset",
         "tiny", "--out", outb, "--format", "torch"]))
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=512, n_positions=96, n_embd=64, n_layer=4, n_head=4))
    missing, unexpected = hf.load_state_dict(torch.load(outb),
                                             strict=False)
    assert not unexpected, unexpected
    assert all(".attn.bias" in k or ".attn.masked_bias" in k
               for k in missing), missing  # torch-internal causal buffers


def test_export_bert_from_sharded_zero1_checkpoint(tmp_path, devices8):
    """The per-shard zero1 checkpoint exports too (sharded restore with an
    sgd template, then the BERT HF mapping)."""
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run

    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "bert_base_zero1", "--model-preset", "tiny",
         "--steps", "2", "--batch-size", "16", "--mesh", "dp=8",
         "--ckpt-dir", ck]))
    out = str(tmp_path / "b.npz")
    res = export_run(export_parser().parse_args(
        ["--config", "bert_base_zero1", "--ckpt-dir", ck,
         "--model-preset", "tiny", "--out", out]))
    z = np.load(out)
    assert res["keys"] == len(z.files) > 20
    assert "bert.encoder.layer.1.attention.self.query.weight" in z.files


def test_export_rejects_missing_checkpoint(tmp_path):
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run
    with pytest.raises(SystemExit, match="no checkpoint"):
        export_run(export_parser().parse_args(
            ["--config", "gpt2_124m", "--ckpt-dir", str(tmp_path / "none"),
             "--model-preset", "tiny", "--out", str(tmp_path / "x.npz")]))


def test_generate_from_sharded_gspmd_checkpoint(tmp_path, devices8):
    """nezha-generate restores the per-shard checkpoint format too (a
    gspmd-trained GPT-2 decodes without an export step)."""
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "2",
         "--batch-size", "8", "--parallel", "gspmd",
         "--mesh", "dp=2,tp=4", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "6",
                "--temperature", "0"])
    assert len(out["tokens"]) == 6
