"""`nezha-generate` CLI: checkpoint restore + KV-cache decode end-to-end."""

import json

import numpy as np
import pytest

from nezha_tpu.cli.generate import build_parser, run as gen_run
from nezha_tpu.cli.train import build_parser as train_parser, run as train_run


def _gen(argv):
    return gen_run(build_parser().parse_args(argv))


def test_generate_from_trained_checkpoint(tmp_path, capsys):
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "3",
         "--batch-size", "8", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "8",
                "--temperature", "0"])
    assert out["prompt_len"] == 3
    assert len(out["tokens"]) == 8
    assert all(0 <= t < 512 for t in out["tokens"])
    assert "restored step 3" in capsys.readouterr().err
    # Greedy decode from the same checkpoint is deterministic.
    again = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                  "--prompt-tokens", "5,17,3", "--max-new-tokens", "8",
                  "--temperature", "0"])
    assert again["tokens"] == out["tokens"]


def test_generate_from_graph_engine_checkpoint(tmp_path, capsys):
    """A GPT-2 trained with --engine graph checkpoints the IR trainer's
    {"params","mu","nu","step"} layout; nezha-generate must read it (the
    params are module-layout, so decode works unchanged)."""
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "3",
         "--batch-size", "8", "--engine", "graph", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "6",
                "--temperature", "0"])
    assert out["prompt_len"] == 3
    assert len(out["tokens"]) == 6
    assert "graph-engine layout" in capsys.readouterr().err

    # nezha-export reads the same layout (HF-keyed npz out).
    from nezha_tpu.cli.export import build_parser as ep, run as erun
    dest = str(tmp_path / "hf.npz")
    summary = erun(ep().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny",
         "--ckpt-dir", ck, "--out", dest, "--format", "npz"]))
    assert summary["keys"] > 0
    import numpy as _np
    assert any("wte" in k for k in _np.load(dest).files)


def test_generate_random_init_and_prompt_file(tmp_path):
    toks = np.asarray([1, 2, 3, 4], np.uint16)
    pf = str(tmp_path / "p.bin")
    toks.tofile(pf)
    out = _gen(["--random-init", "--model-preset", "tiny",
                "--prompt-file", pf, "--max-new-tokens", "4",
                "--temperature", "0.7", "--top-k", "5", "--seed", "3"])
    assert out["prompt_len"] == 4 and len(out["tokens"]) == 4


def test_generate_rejects_bad_inputs(tmp_path):
    with pytest.raises(SystemExit, match="exactly one of"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--max-new-tokens", "4"])
    with pytest.raises(SystemExit, match="comma-separated"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1,x2"])
    with pytest.raises(SystemExit, match=r"in \[0, 512\)"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "9999"])
    with pytest.raises(SystemExit, match="exceeds max_positions"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1,2", "--max-new-tokens", "200"])
    with pytest.raises(SystemExit, match="no checkpoint"):
        _gen(["--ckpt-dir", str(tmp_path / "none"), "--model-preset", "tiny",
              "--prompt-tokens", "1"])


def test_generate_from_hf_weights(tmp_path):
    transformers = pytest.importorskip("transformers")
    cfg = transformers.GPT2Config(vocab_size=128, n_positions=32, n_embd=32,
                                  n_layer=2, n_head=2)
    hf = transformers.GPT2LMHeadModel(cfg)
    hf.save_pretrained(tmp_path / "hf")
    out = _gen(["--hf-dir", str(tmp_path / "hf"),
                "--prompt-tokens", "5,9", "--max-new-tokens", "6",
                "--temperature", "0"])
    assert len(out["tokens"]) == 6
    assert all(0 <= t < 128 for t in out["tokens"])


def test_generate_text_prompt_byte_level(tmp_path):
    """--prompt encodes bytes (the data/pack.py training encoding) and the
    output decodes back to text."""
    out = _gen(["--random-init", "--model-preset", "tiny",
                "--prompt", "hi", "--max-new-tokens", "5",
                "--temperature", "0"])
    assert out["prompt_len"] == 2
    assert isinstance(out["text"], str)
    with pytest.raises(SystemExit, match="exactly one of"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt", "hi", "--prompt-tokens", "1"])
    with pytest.raises(SystemExit, match="empty"):
        _gen(["--random-init", "--model-preset", "tiny", "--prompt", ""])


def test_export_gpt2_npz_and_torch(tmp_path, devices8):
    """nezha-export converts a trained checkpoint to HF-keyed weights; the
    torch format loads straight into GPT2LMHeadModel."""
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run

    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "2",
         "--batch-size", "8", "--ckpt-dir", ck]))

    out = str(tmp_path / "w.npz")
    res = export_run(export_parser().parse_args(
        ["--config", "gpt2_124m", "--ckpt-dir", ck, "--model-preset",
         "tiny", "--out", out]))
    z = np.load(out)
    assert res["keys"] == len(z.files)
    np.testing.assert_array_equal(z["lm_head.weight"],
                                  z["transformer.wte.weight"])  # tied

    transformers = pytest.importorskip("transformers")
    import torch
    outb = str(tmp_path / "w.bin")
    export_run(export_parser().parse_args(
        ["--config", "gpt2_124m", "--ckpt-dir", ck, "--model-preset",
         "tiny", "--out", outb, "--format", "torch"]))
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=512, n_positions=96, n_embd=64, n_layer=4, n_head=4))
    missing, unexpected = hf.load_state_dict(torch.load(outb),
                                             strict=False)
    assert not unexpected, unexpected
    assert all(".attn.bias" in k or ".attn.masked_bias" in k
               for k in missing), missing  # torch-internal causal buffers


def test_export_bert_from_sharded_zero1_checkpoint(tmp_path, devices8):
    """The per-shard zero1 checkpoint exports too (sharded restore with an
    sgd template, then the BERT HF mapping)."""
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run

    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "bert_base_zero1", "--model-preset", "tiny",
         "--steps", "2", "--batch-size", "16", "--mesh", "dp=8",
         "--ckpt-dir", ck]))
    out = str(tmp_path / "b.npz")
    res = export_run(export_parser().parse_args(
        ["--config", "bert_base_zero1", "--ckpt-dir", ck,
         "--model-preset", "tiny", "--out", out]))
    z = np.load(out)
    assert res["keys"] == len(z.files) > 20
    assert "bert.encoder.layer.1.attention.self.query.weight" in z.files


def test_export_rejects_missing_checkpoint(tmp_path):
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run
    with pytest.raises(SystemExit, match="no checkpoint"):
        export_run(export_parser().parse_args(
            ["--config", "gpt2_124m", "--ckpt-dir", str(tmp_path / "none"),
             "--model-preset", "tiny", "--out", str(tmp_path / "x.npz")]))


def test_generate_from_sharded_gspmd_checkpoint(tmp_path, devices8):
    """nezha-generate restores the per-shard checkpoint format too (a
    gspmd-trained GPT-2 decodes without an export step)."""
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "2",
         "--batch-size", "8", "--parallel", "gspmd",
         "--mesh", "dp=2,tp=4", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "6",
                "--temperature", "0"])
    assert len(out["tokens"]) == 6


def _mini_bpe_dir(tmp_path):
    """A tiny but real BPE vocab/merges pair (byte alphabet + two merges)."""
    import json as _json

    from nezha_tpu.data.tokenizer import _bytes_to_unicode
    benc = _bytes_to_unicode()
    vocab = {ch: i for i, ch in enumerate(sorted(benc.values()))}
    h, e, l, o = benc[ord("h")], benc[ord("e")], benc[ord("l")], benc[ord("o")]
    merges = [(h, e), (l, o)]
    for a, b in merges:
        vocab[a + b] = len(vocab)
    d = tmp_path / "tok"
    d.mkdir()
    (d / "vocab.json").write_text(_json.dumps(vocab), encoding="utf-8")
    (d / "merges.txt").write_text(
        "\n".join(f"{a} {b}" for a, b in merges) + "\n", encoding="utf-8")
    return d


def test_generate_with_tokenizer_dir(tmp_path):
    """--tokenizer encodes the text prompt with real BPE and decodes the
    output ids to text (VERDICT r4 item 3: nezha-generate emits real
    text)."""
    d = _mini_bpe_dir(tmp_path)
    out = _gen(["--random-init", "--model-preset", "tiny",
                "--tokenizer", str(d),
                "--prompt", "hello", "--max-new-tokens", "5",
                "--temperature", "0"])
    # "hello" -> "he" + "l" + "lo" under the two merges: 3 prompt ids.
    assert out["prompt_len"] == 3
    assert isinstance(out["text"], str)


def test_generate_hf_dir_auto_tokenizer(tmp_path):
    """An --hf-dir that ships vocab.json/merges.txt gets real-text decode
    with no extra flag."""
    transformers = pytest.importorskip("transformers")
    import shutil

    cfg = transformers.GPT2Config(vocab_size=512, n_positions=32, n_embd=32,
                                  n_layer=2, n_head=2)
    hf = transformers.GPT2LMHeadModel(cfg)
    hf.save_pretrained(tmp_path / "hf")
    tok = _mini_bpe_dir(tmp_path)
    shutil.copy(tok / "vocab.json", tmp_path / "hf" / "vocab.json")
    shutil.copy(tok / "merges.txt", tmp_path / "hf" / "merges.txt")
    out = _gen(["--hf-dir", str(tmp_path / "hf"),
                "--prompt", "hello", "--max-new-tokens", "4",
                "--temperature", "0"])
    assert out["prompt_len"] == 3
    assert isinstance(out["text"], str)


def test_pack_text_cli_roundtrip(tmp_path):
    """nezha-pack-text --tokenizer: the packed corpus decodes back to the
    source text (ids<->text round trip, VERDICT r4 item 3)."""
    from nezha_tpu.cli.pack_text import build_parser as pack_parser
    from nezha_tpu.cli.pack_text import run as pack_run
    from nezha_tpu.data.tokenizer import load_tokenizer

    d = _mini_bpe_dir(tmp_path)
    src = tmp_path / "corpus.txt"
    src.write_text("hello hello world", encoding="utf-8")
    out = tmp_path / "train.tokens.u16"
    res = pack_run(pack_parser().parse_args(
        [str(src), "--tokenizer", str(d), "--out", str(out)]))
    ids = np.fromfile(out, np.uint16)
    assert ids.size == res["tokens"] > 0
    tok = load_tokenizer(str(d))
    assert tok.decode(ids.tolist()) == "hello hello world\n"
    # byte-level default still works and rejects a mismatched suffix
    res2 = pack_run(pack_parser().parse_args(
        [str(src), "--out", str(tmp_path / "b" / "train.tokens.u16")]))
    assert res2["tokens"] == len("hello hello world") + 1
    with pytest.raises(SystemExit, match="u16"):
        pack_run(pack_parser().parse_args(
            [str(src), "--out", str(tmp_path / "x.bin")]))


def test_generate_and_export_from_scan_layers_checkpoint(tmp_path, capsys):
    """A --scan-layers training run (h_scan stacked trunk) round-trips
    through BOTH consumers: nezha-generate auto-detects the layout, and
    nezha-export unstacks to the h{i}-named HF state dict."""
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run

    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "3",
         "--batch-size", "8", "--scan-layers", "--parallel", "single",
         "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "8",
                "--temperature", "0"])
    assert len(out["tokens"]) == 8
    assert "restored step 3" in capsys.readouterr().err
    res = export_run(export_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny",
         "--ckpt-dir", ck, "--format", "npz",
         "--out", str(tmp_path / "hf.npz")]))
    z = np.load(tmp_path / "hf.npz")
    assert any(k.startswith("transformer.h.1.") or "h.1." in k
               for k in z.files), list(z.files)[:5]


def test_generate_scan_layers_sharded_zero1_checkpoint(tmp_path, devices8,
                                                       capsys):
    """Layout detection reads the sharded (zero1) checkpoint's meta index
    too — the COMPLETE-marker-honoring path."""
    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "gpt2_124m", "--model-preset", "tiny", "--steps", "2",
         "--batch-size", "8", "--scan-layers", "--parallel", "zero1",
         "--mesh", "dp=8", "--ckpt-dir", ck]))
    out = _gen(["--ckpt-dir", ck, "--model-preset", "tiny",
                "--prompt-tokens", "5,17,3", "--max-new-tokens", "4",
                "--temperature", "0"])
    assert len(out["tokens"]) == 4
    assert "restored step 2" in capsys.readouterr().err


def test_export_bert_scan_layers_checkpoint(tmp_path, devices8):
    """BERT --scan-layers (layers_scan stacked encoder) exports to the
    layers.N-named HF state dict via detection + unstack."""
    from nezha_tpu.cli.export import build_parser as export_parser
    from nezha_tpu.cli.export import run as export_run

    ck = str(tmp_path / "ck")
    train_run(train_parser().parse_args(
        ["--config", "bert_base_zero1", "--model-preset", "tiny",
         "--steps", "2", "--batch-size", "16", "--scan-layers",
         "--mesh", "dp=8", "--ckpt-dir", ck]))
    export_run(export_parser().parse_args(
        ["--config", "bert_base_zero1", "--model-preset", "tiny",
         "--ckpt-dir", ck, "--format", "npz",
         "--out", str(tmp_path / "hf.npz")]))
    z = np.load(tmp_path / "hf.npz")
    assert any("layer.1." in k or "layers.1." in k for k in z.files), \
        list(z.files)[:6]


def test_generate_num_samples_and_eos_flags(tmp_path):
    """--num-samples batches N continuations of one prompt; greedy
    requires N=1; bad --top-k / --eos-id reject with clear errors."""
    out = _gen(["--random-init", "--model-preset", "tiny",
                "--prompt-tokens", "5,17", "--max-new-tokens", "3",
                "--temperature", "0.9", "--num-samples", "2",
                "--seed", "1"])
    assert out["num_samples"] == 2 and len(out["samples"]) == 2
    assert out["samples"][0]["tokens"] == out["tokens"]
    assert all(len(s["tokens"]) == 3 for s in out["samples"])
    with pytest.raises(SystemExit, match="num-samples"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1", "--num-samples", "0"])
    with pytest.raises(SystemExit, match="greedy"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1", "--temperature", "0",
              "--num-samples", "2"])
    with pytest.raises(SystemExit, match=r"top-k must be in \[1, 512\]"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1", "--top-k", "0"])
    with pytest.raises(SystemExit, match="eos-id"):
        _gen(["--random-init", "--model-preset", "tiny",
              "--prompt-tokens", "1", "--eos-id", "9999"])
