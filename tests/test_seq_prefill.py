"""Sequence-sharded prefill (ISSUE 20): ``prefill_mode="sequence"``
spreads each prefill chunk's attention over the serve mesh's ``tp``
axis (ulysses all-to-all by default, ``lax.ppermute`` ring hops as the
variant — serve/sharded/seq_prefill.py), landing finished blocks in
the same head-sharded paged pool so decode proceeds unchanged.

Pins, per the acceptance list:

- greedy tokens BIT-IDENTICAL to the single-device engine at mesh 2
  across the parity suites: float and int8 pools, ulysses AND ring,
  chunked long prompts through the new ``long_prefill_buckets``,
  shared-prefix partial prefills, speculative decode riding along;
- the frozen program contract re-pinned as ``1 step +
  len(all_prefill_buckets)`` with misses FROZEN after warmup — long
  buckets widen the compiled set deliberately, sequence mode adds
  nothing on top;
- the greedy largest-fit chunk planner: pad-up long tails, big-stride
  long chunks, and EXACT reduction to the classic plan when
  ``long_prefill_buckets=()``;
- config/CLI validation is typed and early (mode and variant names,
  long-bucket monotonicity and range, bucket divisibility by the mesh,
  the single-device refusal) and ``NEZHA_NO_SEQ_PREFILL=1`` is the
  no-config-push rollback;
- the ``serve.prefill.seq`` chaos point: an injected error retires
  ONLY the victim request with zero slot/block/scale leaks per shard;
- the telemetry (``serve.prefill.seq_shards`` gauge,
  ``serve.prefill.ring_hops_total`` counter, ``serve.prefill.seq_s``
  span, the report's ``seq xM`` mode label) is captured schema-clean
  and schema-PINNED (dropping an instrument fails the check).
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.faults import FaultPlan
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig
from nezha_tpu.serve.engine import SpeculativeConfig
from nezha_tpu.serve.sharded import ShardedEngine

CFG = dict(vocab_size=64, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=32)
SCFG = ServeConfig(max_batch_size=3, max_len=32, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32)
# Long-context shape (scaled down): two long buckets above
# max_prefill_len, the 8k/32k document story at test sizes.
LCFG = ServeConfig(max_batch_size=2, max_len=64, max_prefill_len=8,
                   prefill_buckets=(4, 8), long_prefill_buckets=(16, 32),
                   k_max=16, queue_capacity=8, cache_dtype=jnp.float32)
PROMPTS = [[3, 5, 7, 9], [11, 2, 4], [1, 2, 3, 4, 5, 6, 7, 8, 9]]
# Warm every bucket of LCFG.all_prefill_buckets (4, 8, 16, 32): 27
# pads up to 32, 17 to 32, 12 to 16, 3 to 4, 7 to 8.
LONG_PROMPTS = [list(range(1, 28)), list(range(3, 20)),
                list(range(2, 14)), [5, 6, 7], [1] * 7]


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def ref_tokens(model_and_vars):
    """Single-device greedy reference for the shared SCFG/PROMPTS."""
    model, variables = model_and_vars
    return _greedy(Engine(model, variables, SCFG), PROMPTS)


@pytest.fixture(scope="module")
def ref8_tokens(model_and_vars):
    """Single-device int8-pool reference, shared by both seq variants."""
    model, variables = model_and_vars
    i8 = dataclasses.replace(SCFG, kv_dtype="int8")
    return _greedy(Engine(model, variables, i8), PROMPTS)


def _greedy(engine, prompts, max_new=6):
    sched = Scheduler(engine)
    for i, p in enumerate(prompts):
        sched.submit(Request(prompt=list(p), max_new_tokens=max_new,
                             request_id=f"r{i}"))
    sched.run_until_idle(max_iters=400)
    assert not sched.has_work()
    return {k: v.tokens for k, v in sched.results.items()}


def _seq(cfg, **kw):
    return dataclasses.replace(cfg, prefill_mode="sequence", **kw)


# ----------------------------------------------------- parity + contract
def test_seq_ulysses_greedy_parity_bit_identical(model_and_vars,
                                                 ref_tokens):
    """The headline gate: sequence-sharded prefill at mesh 2 (auto →
    ulysses, the bitwise layout — each shard runs the EXACT replicated
    computation on its H/M heads after the all-to-all reshard) emits
    exactly the single-device engine's tokens."""
    model, variables = model_and_vars
    eng = ShardedEngine(model, variables, _seq(SCFG), mesh_devices=2)
    assert eng._seq_active and eng._seq_variant == "ulysses"
    got = _greedy(eng, PROMPTS)
    assert got == ref_tokens
    assert all(v for v in ref_tokens.values())
    # Frozen program contract, sequence mode included: 1 step +
    # len(all_prefill_buckets) entries, misses frozen after warmup.
    stats = eng.compile_stats()
    assert stats["entries"] == 1 + len(SCFG.all_prefill_buckets)
    misses0 = stats["misses"]
    _greedy(eng, [[7, 7, 7], [9] * 7])
    after = eng.compile_stats()
    assert after["entries"] == 1 + len(SCFG.all_prefill_buckets)
    assert after["misses"] == misses0, "seq-mode dispatch recompiled"


def test_seq_ring_greedy_parity(model_and_vars, ref_tokens):
    """The ppermute ring variant (queries + zero out-buffers circulate,
    one flash-kernel call per hop via ``q_offsets``) holds greedy
    parity with the single-device engine on float pools."""
    model, variables = model_and_vars
    eng = ShardedEngine(model, variables,
                        _seq(SCFG, seq_prefill_variant="ring"),
                        mesh_devices=2)
    assert eng._seq_variant == "ring"
    assert _greedy(eng, PROMPTS) == ref_tokens


@pytest.mark.parametrize("variant", ["auto", "ring"])
def test_seq_int8_parity_and_no_leaks(model_and_vars, ref8_tokens,
                                      variant):
    """int8 pools under sequence sharding: the fused epilogue write
    still lands per head shard, greedy tokens match the single-device
    int8 engine, and the per-shard books balance after drain."""
    model, variables = model_and_vars
    i8 = dataclasses.replace(SCFG, kv_dtype="int8")
    eng = ShardedEngine(model, variables,
                        _seq(i8, seq_prefill_variant=variant),
                        mesh_devices=2)
    assert _greedy(eng, PROMPTS) == ref8_tokens
    eng.pool.leak_check()
    assert eng.pool.bytes_resident_per_shard == 0


def test_long_bucket_parity_and_contract(model_and_vars):
    """``long_prefill_buckets``: document-length prompts prefill in a
    handful of wide sequence-sharded dispatches, bit-identical to the
    single-device engine running the SAME widened plan, and the
    program count grows to exactly ``1 + len(all_prefill_buckets)``
    once every bucket is warm."""
    model, variables = model_and_vars
    ref = _greedy(Engine(model, variables, LCFG), LONG_PROMPTS)
    eng = ShardedEngine(model, variables, _seq(LCFG), mesh_devices=2)
    assert _greedy(eng, LONG_PROMPTS) == ref
    stats = eng.compile_stats()
    assert stats["entries"] == 1 + len(LCFG.all_prefill_buckets)
    assert LCFG.all_prefill_buckets == (4, 8, 16, 32)


def test_seq_shared_prefix_parity(model_and_vars):
    """Shared-prefix partial prefill composes: the repeated prompt
    takes a prefix hit (nonzero chunk start into the seq-sharded
    program) and tokens stay bit-identical to the single-device
    engine under the same serial traffic."""
    model, variables = model_and_vars
    long = [5, 17, 3, 9, 11, 2, 7, 23, 41, 8, 1, 13,
            6, 30, 44, 29, 10, 50, 33, 2]
    prompts = [long, [1, 2, 3], long]    # 3rd = prefix hit

    def serial(engine):
        sched = Scheduler(engine)
        outs = []
        for i, p in enumerate(prompts):
            rid = sched.submit(Request(prompt=list(p),
                                       max_new_tokens=6,
                                       request_id=f"r{i}"))
            sched.run_until_idle(max_iters=400)
            outs.append(list(sched.results[rid].tokens))
        return outs

    cfg = dataclasses.replace(LCFG, kv_block_size=4)
    ref = serial(Engine(model, variables, cfg))
    eng = ShardedEngine(model, variables, _seq(cfg), mesh_devices=2)
    got = serial(eng)
    assert got == ref
    assert eng.pool.prefix_hits >= 1


def test_seq_speculative_parity(model_and_vars):
    """Speculative decode rides along: the draft engine's bucket
    programs route through the same seq-prefill hook, accepted/bonus
    tokens bit-identical to the single-device speculative engine."""
    model, variables = model_and_vars
    spec = dataclasses.replace(
        SCFG, speculative=SpeculativeConfig(draft_k=2, draft_layers=1))
    ref = _greedy(Engine(model, variables, spec), PROMPTS)
    got = _greedy(ShardedEngine(model, variables, _seq(spec),
                                mesh_devices=2), PROMPTS)
    assert got == ref


# ------------------------------------------------------- chunk planner
def test_plan_chunks_long_buckets_and_classic_reduction(model_and_vars):
    """The greedy largest-fit planner: pad-up long tails (27 → one
    32-wide dispatch, never 3×8+4), big strides (33 → 32 + 4-tail),
    and EXACT reduction to the classic stride-then-tail plan when
    ``long_prefill_buckets=()``."""
    model, variables = model_and_vars
    eng = Engine(model, variables, LCFG)
    assert eng._plan_chunks(27) == [(0, 27, 32)]
    assert eng._plan_chunks(12) == [(0, 12, 16)]
    assert eng._plan_chunks(33) == [(0, 32, 32), (32, 1, 4)]
    assert eng._plan_chunks(64) == [(0, 32, 32), (32, 32, 32)]
    assert eng.bucket_for(3) == 4 and eng.bucket_for(7) == 8
    classic = Engine(model, variables, dataclasses.replace(
        LCFG, long_prefill_buckets=()))
    assert classic._plan_chunks(27) == [(0, 8, 8), (8, 8, 8),
                                        (16, 8, 8), (24, 3, 4)]
    assert classic._plan_chunks(12) == [(0, 8, 8), (8, 4, 4)]
    assert classic._plan_chunks(3) == [(0, 3, 4)]


# -------------------------------------------------- validation + hatch
def test_env_escape_hatch_kills_seq_prefill(model_and_vars, ref_tokens,
                                            monkeypatch):
    """``NEZHA_NO_SEQ_PREFILL=1`` beats an explicit
    ``prefill_mode="sequence"`` — the engine silently serves the
    replicated path (same tokens, no config push needed)."""
    model, variables = model_and_vars
    monkeypatch.setenv("NEZHA_NO_SEQ_PREFILL", "1")
    eng = ShardedEngine(model, variables, _seq(SCFG), mesh_devices=2)
    assert not eng._seq_active
    assert _greedy(eng, PROMPTS) == ref_tokens


def test_single_device_engine_rejects_sequence_mode(model_and_vars):
    model, variables = model_and_vars
    with pytest.raises(ValueError, match="mesh"):
        Engine(model, variables, _seq(SCFG))


def test_sharded_engine_rejects_indivisible_bucket(model_and_vars):
    model, variables = model_and_vars
    bad = _seq(SCFG, prefill_buckets=(3, 8))
    with pytest.raises(ValueError, match="divisible"):
        ShardedEngine(model, variables, bad, mesh_devices=2)


def test_serve_config_validates_seq_knobs():
    with pytest.raises(ValueError, match="prefill_mode"):
        ServeConfig(prefill_mode="ring")
    with pytest.raises(ValueError, match="seq_prefill_variant"):
        ServeConfig(seq_prefill_variant="deepspeed")
    with pytest.raises(ValueError, match="strictly increasing"):
        dataclasses.replace(LCFG, long_prefill_buckets=(32, 16))
    with pytest.raises(ValueError, match="max_prefill_len"):
        dataclasses.replace(LCFG, long_prefill_buckets=(8, 16))
    with pytest.raises(ValueError, match="max_prefill_len"):
        dataclasses.replace(LCFG, long_prefill_buckets=(16, 128))


def test_cli_rejects_sequence_without_mesh(capsys):
    """``nezha-serve --prefill-mode sequence`` without ``--mesh M>1``
    is a typed SystemExit at argv time, before any engine builds."""
    from nezha_tpu.cli.serve import _build_stack, build_parser
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny",
         "--prefill-mode", "sequence", "--platform", "cpu"])
    with pytest.raises(SystemExit, match="--mesh"):
        _build_stack(args)


# ----------------------------------------------------- chaos + telemetry
def test_chaos_seq_prefill_victim_only_zero_leaks(model_and_vars):
    """The pinned ``serve.prefill.seq`` chaos point: a seeded error at
    the sequence-prefill entry retires ONLY the victim request
    (typed ``error`` finish), everyone else completes, and the
    per-shard books (slots, blocks, int8 scale shapes) balance."""
    model, variables = model_and_vars
    cfg = _seq(dataclasses.replace(SCFG, queue_capacity=16,
                                   kv_dtype="int8"))
    eng = ShardedEngine(model, variables, cfg, mesh_devices=2)
    sched = Scheduler(eng)
    faults.install(FaultPlan.parse("serve.prefill.seq:error@2", seed=7))
    for i in range(8):
        sched.submit(Request(prompt=[(3 + 5 * i) % 64, 2, 9],
                             max_new_tokens=4, request_id=f"c{i}",
                             seed=i))
    sched.run_until_idle(max_iters=600)
    faults.clear()
    assert not sched.has_work()
    assert len(sched.results) == 8
    reasons = [r.finish_reason for r in sched.results.values()]
    assert set(reasons) <= {"length", "error", "eos"}
    assert reasons.count("error") == 1      # the victim, nobody else
    assert eng.pool.num_free == cfg.max_batch_size
    eng.pool.leak_check()
    assert eng.pool.bytes_resident_per_shard == 0


def test_seq_telemetry_capture_and_report(model_and_vars, tmp_path):
    """A sequence-mode ring run captures schema-clean with the PR's
    instruments live — ``serve.prefill.seq_shards`` = mesh size,
    nonzero ``serve.prefill.ring_hops_total``, ``serve.prefill.seq_s``
    spans — and the report's prefill line carries the ``seq x2`` mode
    label plus the ring-hop count. Dropping an instrument FAILS the
    pinned schema."""
    from nezha_tpu.analysis.telemetry_schema import check_run_dir
    model, variables = model_and_vars
    run_dir = str(tmp_path / "run_seq")
    obs.start_run(run_dir, meta={"kind": "seq_prefill_test"})
    try:
        eng = ShardedEngine(model, variables,
                            _seq(SCFG, seq_prefill_variant="ring"),
                            mesh_devices=2)
        _greedy(eng, PROMPTS[:2])
    finally:
        obs.end_run()
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert summary["gauges"]["serve.prefill.seq_shards"] == 2
    assert summary["counters"]["serve.prefill.ring_hops_total"] > 0
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        span_names = {json.loads(ln)["name"] for ln in f if ln.strip()}
    assert "serve.prefill.seq_s" in span_names
    from nezha_tpu.analysis.telemetry_schema import PINNED_SPANS
    assert "serve.prefill.seq_s" in PINNED_SPANS
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "prefill[xla, seq x2]:" in report
    assert "ring hops" in report
    del summary["gauges"]["serve.prefill.seq_shards"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.prefill.seq_shards" in e
               for e in check_run_dir(run_dir))
