"""Checkpoint save/restore/resume round-trips."""

import jax
import numpy as np

from nezha_tpu import data, ops, optim
from nezha_tpu.models.mlp import MLP
from nezha_tpu.train import checkpoint as ckpt
from nezha_tpu.train.loop import Trainer, init_train_state, make_train_step


def _loss_fn(logits, batch):
    return ops.softmax_cross_entropy_with_integer_labels(logits, batch["label"])


def test_checkpoint_roundtrip(tmp_path):
    model = MLP(hidden=(16,))
    opt = optim.adamw(1e-3)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, _loss_fn, donate=False)
    batches = data.mnist_batches(32)
    for _ in range(3):
        state, _ = step(state, next(batches))

    path = ckpt.save_checkpoint(str(tmp_path), state, step=3)
    assert path.endswith("step_00000003.npz")
    assert ckpt.latest_step(str(tmp_path)) == 3

    template = init_train_state(model, opt, jax.random.PRNGKey(0))
    restored, at = ckpt.restore_checkpoint(str(tmp_path), template)
    assert at == 3
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_resume(tmp_path):
    model = MLP(hidden=(16,))
    opt = optim.momentum(0.05)

    t1 = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(7),
                 checkpoint_dir=str(tmp_path), checkpoint_every=5, log_every=5)
    t1.initialize(resume=False)
    t1.fit(data.mnist_batches(32, seed=1), steps=5)
    saved_params = jax.device_get(t1.state["variables"]["params"])

    t2 = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(7),
                 checkpoint_dir=str(tmp_path))
    t2.initialize(resume=True)
    assert t2.global_step == 5
    for a, b in zip(jax.tree_util.tree_leaves(saved_params),
                    jax.tree_util.tree_leaves(t2.state["variables"]["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_keep_last_prunes_oldest(tmp_path):
    from nezha_tpu.train.checkpoint import (latest_step, restore_checkpoint,
                                            save_checkpoint)
    state = {"w": np.arange(4.0)}
    for step in range(1, 6):
        save_checkpoint(tmp_path, {"w": state["w"] + step}, step, keep_last=2)
    left = sorted(p.name for p in tmp_path.glob("step_*.npz"))
    assert left == ["step_00000004.npz", "step_00000005.npz"]
    restored, step = restore_checkpoint(tmp_path, state)
    assert step == 5 == latest_step(tmp_path)
    np.testing.assert_array_equal(restored["w"], state["w"] + 5)
