"""Checkpoint save/restore/resume round-trips."""

import jax
import numpy as np

from nezha_tpu import data, ops, optim
from nezha_tpu.models.mlp import MLP
from nezha_tpu.train import checkpoint as ckpt
from nezha_tpu.train.loop import Trainer, init_train_state, make_train_step


def _loss_fn(logits, batch):
    return ops.softmax_cross_entropy_with_integer_labels(logits, batch["label"])


def test_checkpoint_roundtrip(tmp_path):
    model = MLP(hidden=(16,))
    opt = optim.adamw(1e-3)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, _loss_fn, donate=False)
    batches = data.mnist_batches(32)
    for _ in range(3):
        state, _ = step(state, next(batches))

    path = ckpt.save_checkpoint(str(tmp_path), state, step=3)
    assert path.endswith("step_00000003.npz")
    assert ckpt.latest_step(str(tmp_path)) == 3

    template = init_train_state(model, opt, jax.random.PRNGKey(0))
    restored, at = ckpt.restore_checkpoint(str(tmp_path), template)
    assert at == 3
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_resume(tmp_path):
    model = MLP(hidden=(16,))
    opt = optim.momentum(0.05)

    t1 = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(7),
                 checkpoint_dir=str(tmp_path), checkpoint_every=5, log_every=5)
    t1.initialize(resume=False)
    t1.fit(data.mnist_batches(32, seed=1), steps=5)
    saved_params = jax.device_get(t1.state["variables"]["params"])

    t2 = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(7),
                 checkpoint_dir=str(tmp_path))
    t2.initialize(resume=True)
    assert t2.global_step == 5
    for a, b in zip(jax.tree_util.tree_leaves(saved_params),
                    jax.tree_util.tree_leaves(t2.state["variables"]["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_keep_last_prunes_oldest(tmp_path):
    from nezha_tpu.train.checkpoint import (latest_step, restore_checkpoint,
                                            save_checkpoint)
    state = {"w": np.arange(4.0)}
    for step in range(1, 6):
        save_checkpoint(tmp_path, {"w": state["w"] + step}, step, keep_last=2)
    left = sorted(p.name for p in tmp_path.glob("step_*.npz"))
    assert left == ["step_00000004.npz", "step_00000005.npz"]
    restored, step = restore_checkpoint(tmp_path, state)
    assert step == 5 == latest_step(tmp_path)
    np.testing.assert_array_equal(restored["w"], state["w"] + 5)


# ------------------------------------------- durability + crash-resume
def test_save_embeds_crc_manifest(tmp_path):
    """The CRC32 manifest travels INSIDE the npz (one atomic publish —
    checksums can never pair with another save's data)."""
    import json

    from nezha_tpu.train import checkpoint as ckpt
    state = {"w": np.arange(8.0), "b": np.ones((3,), np.float32)}
    ckpt.save_checkpoint(tmp_path, state, 1)
    with np.load(tmp_path / "step_00000001.npz") as z:
        assert ckpt.MANIFEST_KEY in z.files
        man = json.loads(str(z[ckpt.MANIFEST_KEY]))
    assert man["step"] == 1
    assert set(man["leaves"]) == {"w", "b"}
    assert man["leaves"]["w"]["shape"] == [8]
    assert man["leaves"]["b"]["dtype"] == "float32"
    flat = ckpt.verify_checkpoint(tmp_path, 1)   # intact: verifies clean
    assert ckpt.MANIFEST_KEY not in flat         # stripped for restore
    np.testing.assert_array_equal(flat["w"], state["w"])


def test_try_restore_falls_back_on_torn_newest(tmp_path):
    """The kill-during-save signature — a truncated npz and a stray
    .tmp at the newest step — costs one checkpoint of progress, never
    the run: try_restore returns the previous INTACT step, and an
    explicit restore of the torn step raises the typed error."""
    import pytest

    from nezha_tpu.train import checkpoint as ckpt
    state = {"w": np.arange(4.0)}
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 1}, 1)
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 2}, 2)
    torn = tmp_path / "step_00000002.npz"
    torn.write_bytes(torn.read_bytes()[: torn.stat().st_size // 2])
    (tmp_path / "abc123.tmp").write_bytes(b"partial save junk")
    restored, step = ckpt.try_restore(tmp_path, state)
    assert step == 1
    np.testing.assert_array_equal(restored["w"], state["w"] + 1)
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.restore_checkpoint(tmp_path, state, step=2)
    # resume then continues: the next save REPLACES the torn head and
    # restores normal service
    ckpt.save_checkpoint(tmp_path, {"w": restored["w"] + 1}, 2)
    restored2, step2 = ckpt.try_restore(tmp_path, state)
    assert step2 == 2
    np.testing.assert_array_equal(restored2["w"], state["w"] + 2)


def test_crc_mismatch_detected_and_skipped(tmp_path):
    """A bit-rotted npz that still unzips cleanly is caught by the
    embedded per-leaf CRC32 manifest; try_restore with no intact step
    left returns (None, 0) — train starts fresh instead of loading
    garbage."""
    import pytest

    from nezha_tpu.train import checkpoint as ckpt
    state = {"w": np.arange(4.0)}
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 1}, 1)
    p = tmp_path / "step_00000001.npz"
    with np.load(p) as z:
        man = str(z[ckpt.MANIFEST_KEY])
    # same leaves + original manifest, different bytes: valid zip,
    # wrong CRC (the bit-rot signature)
    np.savez(p, w=np.zeros(4), **{ckpt.MANIFEST_KEY: np.asarray(man)})
    with pytest.raises(ckpt.CheckpointCorrupt, match="CRC32"):
        ckpt.verify_checkpoint(tmp_path, 1)
    restored, step = ckpt.try_restore(tmp_path, state)
    assert restored is None and step == 0


def test_manifestless_checkpoint_still_loads(tmp_path):
    """Pre-manifest saves (older runs: a plain npz with no embedded
    manifest) restore on a clean unzip alone."""
    from nezha_tpu.train import checkpoint as ckpt
    state = {"w": np.arange(4.0)}
    np.savez(tmp_path / "step_00000001.npz", w=state["w"] + 1)
    restored, step = ckpt.try_restore(tmp_path, state)
    assert step == 1
    np.testing.assert_array_equal(restored["w"], state["w"] + 1)


def test_try_restore_survives_concurrently_pruned_step(tmp_path):
    """A checkpoint deleted between the directory listing and the open
    (multi-host pruner race) is not corruption — try_restore walks past
    it to the next intact step instead of raising FileNotFoundError."""
    from nezha_tpu.train import checkpoint as ckpt
    state = {"w": np.arange(4.0)}
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 1}, 1)
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 2}, 2)
    real_verify = ckpt.verify_checkpoint
    (tmp_path / "step_00000002.npz").unlink()   # "pruned" after listing

    steps = ckpt.checkpoint_steps(tmp_path)
    assert steps == [1]                          # listing sees reality...
    # ...but simulate the race: walk a stale listing through try_restore
    import unittest.mock as mock
    with mock.patch.object(ckpt, "checkpoint_steps",
                           return_value=[1, 2]):
        restored, step = ckpt.try_restore(tmp_path, state)
    assert step == 1
    np.testing.assert_array_equal(restored["w"], state["w"] + 1)
    assert ckpt.verify_checkpoint is real_verify


def test_kill_during_save_fault_leaves_previous_intact(tmp_path):
    """Fault-plan drill at the checkpoint.save point (between the npz
    tmp write and publication): the save dies, no partial step becomes
    visible, and resume still lands on the previous step."""
    import pytest

    from nezha_tpu import faults
    from nezha_tpu.train import checkpoint as ckpt
    state = {"w": np.arange(4.0)}
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 1}, 1)
    faults.install(faults.FaultPlan.parse("checkpoint.save:error@1"))
    try:
        with pytest.raises(faults.InjectedFault):
            ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 2}, 2)
    finally:
        faults.clear()
    assert not (tmp_path / "step_00000002.npz").exists()
    assert ckpt.latest_step(tmp_path) == 1
    restored, step = ckpt.try_restore(tmp_path, state)
    assert step == 1
    np.testing.assert_array_equal(restored["w"], state["w"] + 1)
    # the interrupted step saves cleanly on retry
    ckpt.save_checkpoint(tmp_path, {"w": state["w"] + 2}, 2)
    assert ckpt.try_restore(tmp_path, state)[1] == 2


def test_trainer_resumes_past_torn_checkpoint(tmp_path):
    """End to end: training saved steps 3 and 6, the newest save was
    torn by a crash — resume falls back to step 3 and training
    CONTINUES from there."""
    model = MLP(hidden=(16,))
    opt = optim.momentum(0.05)
    t1 = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(7),
                 checkpoint_dir=str(tmp_path), checkpoint_every=3,
                 log_every=10)
    t1.initialize(resume=False)
    t1.fit(data.mnist_batches(32, seed=1), steps=6)
    torn = tmp_path / "step_00000006.npz"
    assert torn.exists()
    torn.write_bytes(torn.read_bytes()[:128])

    t2 = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(7),
                 checkpoint_dir=str(tmp_path), checkpoint_every=3,
                 log_every=10)
    t2.initialize(resume=True)
    assert t2.global_step == 3            # newest INTACT step
    t2.fit(data.mnist_batches(32, seed=1), steps=3)   # resumes training
    assert t2.global_step == 6
