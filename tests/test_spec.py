"""Speculative decoding (ISSUE 13): draft→verify→accept inside the
device-resident horizon scan.

Covers the parity gates (greedy outputs bit-identical speculative vs
classic on both KV layouts, h=1 and h=8, chunked prefill included; the
lossless rejection-sampling law on the sampling kernels; sampled spec
outputs horizon-invariant), the on-device completion semantics (EOS
inside an accepted prefix freezes the row mid-window — overshoot never
reaches the client), the frozen TWO-ENGINE program-count contract
(target: 1 step + len(prefill_buckets); draft: len(prefill_buckets) —
the draft's decode lives inside the one fused step program), the
mirrored draft-pool slot lifecycle (lockstep alloc/free, leak_check
drift oracle), the pinned ``serve.spec.verify`` fault point (NaN
retires only the victim; an error rule rides the bounded-retry
envelope), the seeded chaos acceptance with zero slot/block leaks in
BOTH pools, the schema-pinned ``serve.spec.*`` instruments + report
line, and the benchmark's ``spec{...}`` record block.
"""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.models.generate import generate
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig
from nezha_tpu.serve.engine import SpeculativeConfig, self_draft
from nezha_tpu.serve.sampling import accept_mask, residual_logits
from nezha_tpu.serve.slots import PagedSlotPool

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
SCFG = ServeConfig(max_batch_size=3, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=16,
                   cache_dtype=jnp.float32, kv_block_size=4)
SPEC = SpeculativeConfig(draft_k=2, draft_layers=1)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for sub in ("tools", "benchmarks"):
    p = os.path.join(_ROOT, sub)
    if p not in sys.path:
        sys.path.insert(0, p)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


def _drain(sched, max_iters=400):
    sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"


def _requests():
    """A mixed load: short/bucketed/chunked prompts, greedy and
    sampled rows (prompt 13 > max_prefill_len=8 -> chunked)."""
    return [
        Request(prompt=[5, 17, 3, 42], max_new_tokens=8,
                request_id="g0"),
        Request(prompt=[7, 7], max_new_tokens=7, temperature=0.9,
                top_k=10, seed=7, request_id="s0"),
        Request(prompt=[(3 * i + 2) % 97 for i in range(13)],
                max_new_tokens=6, request_id="g1"),
        Request(prompt=[11, 4, 9, 2, 8, 1], max_new_tokens=8,
                temperature=0.7, top_k=12, seed=3, request_id="s1"),
    ]


def _run(model, variables, cfg):
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    for r in _requests():
        sched.submit(r)
    _drain(sched)
    return eng, {k: (v.tokens, v.finish_reason)
                 for k, v in sched.results.items()}


# ------------------------------------------------------------ parity
@pytest.mark.parametrize("layout", ["paged", "dense"])
def test_greedy_parity_spec_vs_classic_bit_identical(model_and_vars,
                                                     layout):
    """The ISSUE 13 parity gate: with speculative ON every request's
    output (greedy AND sampled-within-spec across horizons) matches —
    greedy rows bit-identical to the CLASSIC engine and to one-shot
    generate(), at h=1 and h=8, chunked prompts included. Every
    accepted draft token is verified against the target, so the draft
    (a 1-layer early-exit) can only change speed, never tokens."""
    model, variables = model_and_vars
    outs = {}
    for h in (1, 8):
        base = dataclasses.replace(SCFG, kv_layout=layout,
                                   decode_horizon=h)
        _, classic = _run(model, variables, base)
        eng, spec = _run(model, variables,
                         dataclasses.replace(base, speculative=SPEC))
        # Greedy rows: bit-identical to classic, reason and all.
        for rid in ("g0", "g1"):
            assert spec[rid] == classic[rid], (layout, h, rid)
        # The speculation actually ran and accepted draft tokens.
        assert eng.spec_verifies > 0
        assert eng.spec_accepted > 0
        outs[h] = spec
    # Spec outputs (sampled rows included) are horizon-invariant.
    assert outs[1] == outs[8]
    ref = np.asarray(generate(
        model, variables, np.asarray([[5, 17, 3, 42]], np.int32),
        max_new_tokens=8, temperature=0.0,
        cache_dtype=jnp.float32))[0, 4:]
    assert outs[8]["g0"][0] == ref.tolist()


def test_rejection_sampling_law_monte_carlo():
    """The lossless-speculative-sampling pin on the kernels themselves:
    draw d ~ q, accept when u·q(d) <= p(d), else resample from
    ``residual_logits(p, q)`` — the emitted marginal must equal p
    EXACTLY (checked empirically to Monte Carlo noise). This is the
    distribution-invariance half of the parity gate: greedy rows are
    pinned bit-identical above; sampled rows are pinned lawful here."""
    v, n = 8, 200_000
    key = jax.random.PRNGKey(0)
    kp, kq, kd, ku, kr = jax.random.split(key, 5)
    p = jax.nn.softmax(jax.random.normal(kp, (v,)) * 1.5)
    q = jax.nn.softmax(jax.random.normal(kq, (v,)) * 1.5)
    d = jax.random.categorical(kd, jnp.log(q), shape=(n,))
    u = jax.random.uniform(ku, (n,))
    acc = accept_mask(
        d[:, None], jnp.broadcast_to(p, (n, 1, v)),
        jnp.broadcast_to(q, (n, 1, v)), u[:, None],
        jnp.zeros((n,), bool), jnp.zeros((n, 1), jnp.int32))[:, 0]
    res = jax.random.categorical(
        kr, jnp.broadcast_to(residual_logits(p[None, :], q[None, :]),
                             (n, v)), axis=-1)
    emitted = jnp.where(acc, d, res)
    emp = jnp.bincount(emitted, length=v) / n
    tv = 0.5 * float(jnp.abs(emp - p).sum())
    assert tv < 0.01, f"total variation {tv} vs target p"
    # Sanity: the test is discriminating — q itself is far from p.
    assert 0.5 * float(jnp.abs(q - p).sum()) > 0.05
    # Boundary regression: jax.random.uniform can return EXACTLY 0; a
    # draft token the target assigns zero probability must still be
    # rejected (u·q < p is strict — `<=` would emit a token classic
    # sampling never could).
    p0 = jnp.array([[[0.0, 1.0]]])          # target: token 0 impossible
    q0 = jnp.array([[[1.0, 0.0]]])          # draft proposes token 0
    acc0 = accept_mask(jnp.array([[0]]), p0, q0, jnp.array([[0.0]]),
                       jnp.zeros((1,), bool), jnp.zeros((1, 1),
                                                        jnp.int32))
    assert not bool(acc0[0, 0])


def test_sampled_rejections_survive_bf16_and_health_tripwire(
        model_and_vars):
    """Regression (found driving the real server): after a REJECTION
    the carried residual log-probs hold floor values for zero-mass
    entries — the floor must stay a NORMAL fp32 number, because XLA's
    CPU backend flushes denormals to zero and ``log(0) = -inf`` would
    trip the carried-logits health check, retiring a healthy sampled
    row as 'non-finite logits'. A shallow draft at bf16 cache dtype
    (the CLI default) forces rejections; the request must finish
    LENGTH, never ERROR, and keep its residual logits finite."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, cache_dtype=jnp.bfloat16,
                              speculative=SPEC)
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    rid = sched.submit(Request(prompt=[7, 7, 9], max_new_tokens=10,
                               temperature=0.8, top_k=40, seed=7))
    _drain(sched)
    res = sched.results[rid]
    assert res.finish_reason == "length", res.error
    assert len(res.tokens) == 10
    # The machinery genuinely rejected along the way (the residual
    # path fired), and the carried logits stayed finite through it.
    assert eng.spec_accepted < eng.spec_verifies * SPEC.draft_k
    assert bool(np.isfinite(np.asarray(eng.last_logits)).all())


# ------------------------------------------- on-device completion
def test_eos_inside_accepted_prefix_freezes_row(model_and_vars):
    """An EOS landing INSIDE the accepted prefix of a verify window
    cuts emission at the EOS on device: emitted stops there, the cache
    position freezes (no K/V appended past it), the window's overshoot
    columns are pad — and the client sees tokens ending exactly at the
    EOS. The draft is the full-depth identity (accept rate ~1), so the
    cut is the EOS mask, not a rejection."""
    model, variables = model_and_vars
    spec = SpeculativeConfig(draft_k=5, draft_layers=None)
    cfg = dataclasses.replace(SCFG, speculative=spec)
    kw = dict(prompt=[5, 17, 3, 42], max_new_tokens=6, temperature=0.9,
              top_k=10, seed=7)
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    probe = sched.submit(Request(**kw))
    _drain(sched)
    seq = sched.results[probe].tokens
    stop = next(i for i in range(1, len(seq)) if seq[i] not in seq[:i])
    eos, ref = seq[stop], seq[:stop + 1]
    assert 1 <= stop < 5          # genuinely inside the first window

    eng2 = Engine(model, variables, cfg)
    eng2.prefill(0, kw["prompt"], seed=7, temperature=0.9, top_k=10,
                 eos_id=eos, max_new_tokens=6)
    active = np.zeros((SCFG.max_batch_size,), bool)
    active[0] = True
    tok, emitted = eng2.step(active)
    assert tok.shape == (SCFG.max_batch_size, 6)  # H * (k+1), cap 6
    assert emitted[0] == stop + 1
    assert tok[0, :stop + 1].tolist() == ref      # ends WITH the eos
    assert (tok[0, stop + 1:] == SCFG.pad_id).all()
    assert (emitted[1:] == 0).all()
    assert int(np.asarray(eng2.positions)[0]) == \
        len(kw["prompt"]) + stop + 1

    sched2 = Scheduler(Engine(model, variables, cfg))
    rid = sched2.submit(Request(**kw, eos_id=eos))
    _drain(sched2)
    res = sched2.results[rid]
    assert res.finish_reason == "eos"
    assert res.tokens == ref


def test_spec_ttft_and_tpot_credited_per_accepted_token(
        model_and_vars, tmp_path):
    """A verify dispatch emitting e tokens observes serve.tpot_s once
    PER ACCEPTED token (block dt split over e) and credits TTFT at the
    first accepted token's position within the block — not at the
    block end (the PR 5 move, denominator = accepted count)."""
    model, variables = model_and_vars
    obs.start_run(str(tmp_path / "spec_tpot"), meta={"kind": "serve"})
    try:
        spec = SpeculativeConfig(draft_k=7, draft_layers=None)
        eng = Engine(model, variables,
                     dataclasses.replace(SCFG, max_batch_size=1,
                                         speculative=spec))
        sched = Scheduler(eng)
        rid = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=8))
        _drain(sched)
        assert eng.step_calls == 1          # all 8 tokens, one verify
        h = obs.histogram("serve.tpot_s")
        assert h.count == 8                 # one observation per token
        res = sched.results[rid]
        assert res.ttft_s < res.latency_s
        # serve.decode.horizon records the tokens-per-dispatch CEILING
        # h * (draft_k + 1).
        dh = obs.histogram("serve.decode.horizon")
        assert dh.summary()["max"] == 8
    finally:
        obs.end_run()


# ------------------------------------------------ program contract
def test_two_engine_frozen_program_counts(model_and_vars):
    """The frozen program contract counted PER ENGINE: target keeps
    exactly 1 step + len(prefill_buckets) programs (the whole
    draft→verify→accept loop is baked into the one step program) and
    the draft engine exactly len(prefill_buckets) bucket prefills (its
    decode never dispatches on its own) — all misses frozen after
    warmup, and >1 token accepted per verify dispatch on the ledger."""
    model, variables = model_and_vars
    eng = Engine(model, variables,
                 dataclasses.replace(SCFG, speculative=SPEC))
    sched = Scheduler(eng)
    n_buckets = len(SCFG.prefill_buckets)

    def wave(tag):
        for i in range(4):
            sched.submit(Request(
                prompt=[3 + i, 1, 4] * (1 + i % 2),   # both buckets
                max_new_tokens=8, request_id=f"{tag}{i}"))
        _drain(sched)

    wave("a")
    t, d = eng.compile_stats(), eng.draft_compile_stats()
    assert t["entries"] == t["misses"] == 1 + n_buckets
    assert d["entries"] == d["misses"] == n_buckets
    wave("b")                                  # steady state: no growth
    t2, d2 = eng.compile_stats(), eng.draft_compile_stats()
    assert (t2["entries"], t2["misses"]) == \
        (1 + n_buckets, 1 + n_buckets)
    assert (d2["entries"], d2["misses"]) == (n_buckets, n_buckets)
    assert t2["hits"] > t["hits"]
    # The headline ledger: more than one token accepted per verify.
    assert eng.spec_verifies > 0
    assert (eng.spec_accepted + eng.spec_verifies) \
        / eng.spec_verifies > 1.0


def test_draft_pool_mirrors_slot_lifecycle(model_and_vars):
    """The draft pool shadows the target pool's slot lifecycle by
    INDEX: alloc claims the same slot in both, free releases both in
    the same call, and the leak oracle catches lifecycle drift."""
    model, variables = model_and_vars
    pool = PagedSlotPool(model, 3, 48, jnp.float32, block_size=4)
    draft, dvars = self_draft(model, variables, 1)
    del dvars
    mirror = PagedSlotPool(draft, 3, 48, jnp.float32, block_size=4)
    pool.mirror = mirror
    s = pool.alloc()
    assert s is not None and s not in mirror._free_slots
    pool.free(s)
    assert sorted(mirror._free_slots) == sorted(pool._free_slots)
    pool.leak_check()
    # Claiming a slot the mirror already holds must surface.
    s = pool.alloc()
    with pytest.raises(ValueError):
        mirror.claim(s)
    # Drift: the mirror losing lockstep must surface, not corrupt.
    mirror.free(s)
    with pytest.raises(AssertionError, match="draft pool slot drift"):
        pool.leak_check()
    mirror.claim(s)                           # restore lockstep
    pool.free(s)
    pool.leak_check()


def test_speculative_config_validation(model_and_vars):
    model, variables = model_and_vars
    with pytest.raises(ValueError, match="draft_k"):
        ServeConfig(speculative=SpeculativeConfig(draft_k=0))
    with pytest.raises(ValueError, match="draft_layers"):
        ServeConfig(speculative=SpeculativeConfig(draft_layers=0))
    # argv/JSON convenience: a dict coerces to SpeculativeConfig.
    cfg = ServeConfig(speculative={"draft_k": 2})
    assert isinstance(cfg.speculative, SpeculativeConfig)
    with pytest.raises(ValueError, match="draft_layers"):
        self_draft(model, variables, CFG["num_layers"] + 1)
    with pytest.raises(ValueError, match="draft_variables"):
        Engine(model, variables,
               dataclasses.replace(SCFG, speculative=SPEC),
               draft_model=model)
    other = GPT2(GPT2Config(**{**CFG, "vocab_size": 96}))
    with pytest.raises(ValueError, match="vocab"):
        Engine(model, variables,
               dataclasses.replace(SCFG, speculative=SPEC),
               draft_model=other,
               draft_variables=other.init(jax.random.PRNGKey(1)))
    # Early-exit self-draft: first N blocks, shared trunk leaves.
    draft, dvars = self_draft(model, variables, 1)
    assert draft.cfg.num_layers == 1
    assert dvars["params"]["wte"] is variables["params"]["wte"]


# ------------------------------------------------- faults + chaos
def test_spec_verify_nan_retires_only_victim(model_and_vars):
    """The pinned serve.spec.verify fault point, nan rule: one row's
    carried logits are poisoned after a verify dispatch; the NEXT
    dispatch's in-program tripwire freezes that row and the scheduler
    retires it typed — batch neighbors finish clean, zero leaks in
    either pool."""
    model, variables = model_and_vars
    eng = Engine(model, variables,
                 dataclasses.replace(SCFG, speculative=SPEC))
    sched = Scheduler(eng)
    faults.install(faults.FaultPlan.parse(
        "serve.spec.verify:nan@1x1", seed=3))
    try:
        rids = [sched.submit(Request(prompt=[9 + i, 2, 5],
                                     max_new_tokens=8,
                                     request_id=f"v{i}"))
                for i in range(3)]
        _drain(sched)
    finally:
        faults.clear()
    reasons = {r: sched.results[r].finish_reason for r in rids}
    assert sorted(reasons.values()) == ["error", "length", "length"]
    victim = next(r for r, why in reasons.items() if why == "error")
    assert sched.results[victim].error
    assert eng.pool.num_free == SCFG.max_batch_size
    eng.pool.leak_check()                     # recurses into the mirror


def test_spec_verify_error_rides_bounded_retry(model_and_vars):
    """An error rule at serve.spec.verify raises typed InjectedFault
    out of engine.step; the scheduler's single bounded retry redials
    and every request still finishes clean."""
    model, variables = model_and_vars
    eng = Engine(model, variables,
                 dataclasses.replace(SCFG, speculative=SPEC))
    sched = Scheduler(eng)
    faults.install(faults.FaultPlan.parse(
        "serve.spec.verify:error@2x1", seed=0))
    try:
        rids = [sched.submit(Request(prompt=[4 + i, 8], max_new_tokens=6,
                                     request_id=f"e{i}"))
                for i in range(2)]
        _drain(sched)
    finally:
        faults.clear()
    assert all(sched.results[r].finish_reason == "length" for r in rids)
    eng.pool.leak_check()


def test_spec_chaos_zero_leaks_both_pools(model_and_vars, tmp_path):
    """The chaos acceptance with speculation ON at horizon 4: seeded
    prefill errors + verify NaN bursts + kv.bind failures over 16
    requests. Every request gets exactly one typed result, zero slot
    leaks and zero block leaks in BOTH the target and draft pools (the
    leak oracle recurses through the mirror), the two-engine program
    set stays frozen, and the artifacts pass the pinned schema
    including the serve.spec.* instruments and the report's
    speculation line."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "chaos_spec")
    obs.start_run(run_dir, meta={"kind": "chaos_spec"})
    try:
        cfg = dataclasses.replace(SCFG, decode_horizon=4,
                                  speculative=SPEC)
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        faults.install(faults.FaultPlan.parse(
            "serve.prefill:error%0.08;serve.spec.verify:nan%0.05;"
            "serve.kv.bind:error%0.03", seed=7))
        try:
            prefix = [(3 * i + 5) % 97 for i in range(8)]
            rids = []
            for i in range(16):
                prompt = (prefix + [i % 97, (2 * i) % 97]
                          if i % 2 else
                          [(11 * i + j) % 97 for j in range(6)])
                rids.append(sched.submit(Request(
                    prompt=prompt, max_new_tokens=6,
                    temperature=0.8 if i % 3 == 0 else 0.0,
                    top_k=10 if i % 3 == 0 else None, seed=i,
                    request_id=f"c{i}")))
            _drain(sched)
        finally:
            faults.clear()
        assert set(rids) <= set(sched.results)
        reasons = {sched.results[r].finish_reason for r in rids}
        assert reasons <= {"length", "error"}
        assert eng.pool.num_free == cfg.max_batch_size
        eng.pool.leak_check()                 # target + mirror oracles
        stats = eng.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(cfg.prefill_buckets)
        d = eng.draft_compile_stats()
        assert d["entries"] == d["misses"] == len(cfg.prefill_buckets)
        eng.pool.clear_prefix_cache()
        eng.pool.leak_check()
        assert eng.pool.blocks_used == 0
        assert eng.draft_pool.blocks_used == 0
    finally:
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert summary["counters"]["serve.spec.draft_tokens_total"] > 0
    assert summary["counters"]["serve.spec.accepted_total"] > 0
    assert summary["histograms"]["serve.spec.accepted_len"]["count"] > 0
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "speculation:" in report and "tokens/verify" in report
    # Dropping a spec instrument must FAIL the pinned schema.
    del summary["histograms"]["serve.spec.accepted_len"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.spec.accepted_len" in e
               for e in check_run_dir(run_dir))


# --------------------------------------------------------- benchmark
def test_serving_benchmark_spec_record(tmp_path):
    """benchmarks/serving.py --speculative: the record gains the
    spec{draft_k, accept_rate, tokens_per_verify, ...} block and the
    tiny closed loop already accepts >1 token per verify dispatch."""
    import serving as serving_bench

    args = serving_bench.build_parser().parse_args([
        "--requests", "6", "--concurrency", "2",
        "--max-batch-size", "2", "--max-len", "48",
        "--max-prefill-len", "8", "--prompt-len", "4",
        "--max-new-tokens", "8", "--sample-fraction", "0",
        "--decode-horizon", "1", "--speculative", "--draft-k", "3",
        "--draft-layers", "1", "--platform", "cpu",
        "--run-dir", str(tmp_path / "specbench")])
    record = serving_bench.run(args)
    rec = record["by_horizon"]["1"] if "by_horizon" in record else record
    sp = rec["spec"]
    assert sp["draft_k"] == 3 and sp["draft_layers"] == 1
    assert sp["verifies"] > 0
    assert sp["draft_tokens"] == sp["verifies"] * 3
    assert 0.0 < sp["accept_rate"] <= 1.0
    assert sp["tokens_per_verify"] > 1.0
    assert rec["tokens_per_sec"] > 0
