"""Telemetry-subsystem tests: registry counter/span semantics, the
run-scoped sink's JSONL/summary round trip, the disabled-mode no-op fast
paths, and the frozen-schema validator (tools/check_telemetry_schema.py)."""

import json
import os
import sys

import pytest

from nezha_tpu import obs
from nezha_tpu.obs import registry as obs_registry

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "tools"))
from check_telemetry_schema import check_run_dir  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_obs():
    """Telemetry is process-wide: every test starts disabled and empty,
    and cannot leak an enabled registry into the rest of the suite."""
    obs.end_run()
    obs.REGISTRY.reset()
    yield
    obs.end_run()
    obs.REGISTRY.reset()


# ------------------------------------------------------ registry semantics
def test_counter_gauge_histogram_when_enabled():
    obs.enable()
    try:
        c = obs.counter("c")
        c.inc()
        c.inc(4)
        assert c.value == 5
        assert obs.counter("c") is c  # get-or-create, process-wide
        obs.gauge("g").set(3)
        assert obs.gauge("g").value == 3.0
        h = obs.histogram("h")
        for v in [1.0, 2.0, 3.0, 4.0]:
            h.observe(v)
        s = h.summary()
        assert s["count"] == 4 and s["min"] == 1.0 and s["max"] == 4.0
        assert s["sum"] == 10.0 and 1.0 <= s["p50"] <= 4.0
    finally:
        obs.disable()


def test_histogram_reservoir_bounds_memory():
    obs.enable()
    try:
        h = obs_registry.Histogram("big", cap=64)
        for i in range(10000):
            h.observe(float(i))
        assert h.count == 10000 and h.max == 9999.0
        assert len(h._samples) < 128  # decimated, not unbounded
        assert h.percentile(50) == pytest.approx(5000, rel=0.2)
    finally:
        obs.disable()


def test_histogram_reservoir_unbiased_over_long_runs():
    """The regression the reservoir switch fixes: a distribution shift
    AFTER the reservoir first fills must dominate the percentiles when
    it dominates the stream — the old stride decimation anchored its
    kept set to the startup prefix, biasing long-run percentiles toward
    the first ~cap observations."""
    obs.enable()
    try:
        h = obs_registry.Histogram("shift", cap=256)
        # Fill the reservoir entirely with the startup regime, then
        # stream 20x as many observations of the steady-state regime.
        for _ in range(256):
            h.observe(1.0)
        for _ in range(256 * 20):
            h.observe(100.0)
        assert h.count == 256 * 21
        assert len(h._samples) == 256        # still bounded
        # ~95% of the stream is the late regime: p50 (and even p10)
        # must sit there. Exact streaming stats are unaffected.
        assert h.percentile(50) == 100.0
        s = h.summary()
        assert s["min"] == 1.0 and s["max"] == 100.0
        late_frac = sum(1 for v in h._samples if v == 100.0) / 256
        assert late_frac == pytest.approx(20 / 21, abs=0.08)
        # Seeded per-name RNG: the same stream reproduces the same
        # reservoir (captures are deterministic).
        h2 = obs_registry.Histogram("shift", cap=256)
        for _ in range(256):
            h2.observe(1.0)
        for _ in range(256 * 20):
            h2.observe(100.0)
        assert h2._samples == h._samples
    finally:
        obs.disable()


def test_span_records_duration_and_attrs():
    obs.enable()
    try:
        with obs.span("work", phase="test") as sp:
            sp.set(extra=1)
        rec = obs.REGISTRY.spans[-1]
        assert rec["name"] == "work"
        assert rec["attrs"] == {"phase": "test", "extra": 1}
        assert rec["t1"] >= rec["t0"] and rec["dur_s"] >= 0.0
    finally:
        obs.disable()


def test_span_marks_errors():
    obs.enable()
    try:
        with pytest.raises(ValueError):
            with obs.span("boom"):
                raise ValueError("x")
        assert obs.REGISTRY.spans[-1]["attrs"]["error"] == "ValueError"
    finally:
        obs.disable()


# --------------------------------------------------- disabled-mode no-ops
def test_disabled_mode_is_noop_without_allocation():
    """The zero-overhead contract: disabled spans are ONE shared
    singleton (identity, not equality — no per-call allocation) and
    counters/gauges/histograms never record."""
    assert not obs.enabled()
    assert obs.span("a") is obs.NULL_SPAN
    assert obs.span("b", k=1) is obs.NULL_SPAN  # attrs don't allocate one
    with obs.span("c") as sp:
        assert sp is obs.NULL_SPAN
        sp.set(x=2)  # no-op, chainable
    c = obs.counter("n")
    c.inc(100)
    assert c.value == 0
    obs.gauge("g").set(9)
    assert obs.gauge("g").value == 0.0
    h = obs.histogram("h")
    h.observe(5.0)
    assert h.count == 0 and not h._samples
    obs.record_metrics(1, {"loss": 1.0})
    obs.record_collective("all_reduce", 1024)
    assert obs.REGISTRY.spans == []
    # Instruments exist (get-or-create) but recorded nothing.
    assert all(v == 0 for v in obs.REGISTRY.snapshot()["counters"].values())


# ------------------------------------------------------- trace context
def test_span_adopts_ambient_trace_and_nests():
    obs.enable()
    try:
        with obs.trace_context("t" * 16):
            with obs.span("outer") as sp:
                assert sp.trace_id == "t" * 16
                assert sp.parent_id is None
                with obs.span("inner") as child:
                    assert child.trace_id == "t" * 16
                    assert child.parent_id == sp.span_id
        outer = [r for r in obs.REGISTRY.spans if r["name"] == "outer"]
        inner = [r for r in obs.REGISTRY.spans if r["name"] == "inner"]
        assert outer[0]["trace_id"] == "t" * 16
        assert inner[0]["parent_id"] == outer[0]["span_id"]
        # outside any context, spans carry no trace fields at all
        with obs.span("plain"):
            pass
        plain = [r for r in obs.REGISTRY.spans if r["name"] == "plain"]
        assert "trace_id" not in plain[0]
    finally:
        obs.disable()


def test_traced_span_gates_on_ambient_trace():
    obs.enable()
    try:
        assert obs.traced_span("x") is obs.NULL_SPAN  # no ambient trace
        with obs.trace_context("a" * 16):
            with obs.traced_span("x"):
                pass
        assert [r["name"] for r in obs.REGISTRY.spans] == ["x"]
    finally:
        obs.disable()
    assert obs.traced_span("x") is obs.NULL_SPAN      # disabled


def test_emit_span_retroactive_record():
    obs.enable()
    try:
        obs.emit_span("later", 10.0, 12.5, trace_id="b" * 16, k=1)
        rec = obs.REGISTRY.spans[-1]
        assert rec["t0"] == 10.0 and rec["dur_s"] == 2.5
        assert rec["trace_id"] == "b" * 16 and rec["span_id"]
        assert rec["attrs"] == {"k": 1}
    finally:
        obs.disable()
    obs.emit_span("noop", 0.0, 1.0)            # disabled: records nothing
    assert obs.REGISTRY.spans[-1]["name"] == "later"


def test_mint_trace_id_sampling_and_disable():
    assert obs.mint_trace_id() is None         # disabled -> no tracing
    obs.enable()
    try:
        tid = obs.mint_trace_id()
        assert isinstance(tid, str) and len(tid) == 16
        obs.set_trace_sample(0.0)
        assert obs.mint_trace_id() is None     # sampled out entirely
        obs.set_trace_sample(1.0)
        assert obs.mint_trace_id() is not None
        with pytest.raises(ValueError):
            obs.set_trace_sample(1.5)
    finally:
        obs.set_trace_sample(1.0)
        obs.disable()


def test_stats_snapshot_matches_pinned_schema():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "tools"))
    from check_telemetry_schema import check_stats_payload
    obs.enable()
    try:
        obs.counter("train.steps").inc(3)
        obs.gauge("g").set(2.0)
        obs.histogram("h").observe(1.0)
        payload = obs.stats_snapshot()
    finally:
        obs.disable()
    assert check_stats_payload(payload) == []
    assert payload["kind"] == "replica" and payload["enabled"] is True
    assert payload["counters"]["train.steps"] == 3
    assert payload["histograms"]["h"]["count"] == 1
    # disabled snapshots still validate (enabled: false, curl-able)
    assert check_stats_payload(obs.stats_snapshot()) == []
    # malformed payloads are named, not waved through
    assert check_stats_payload({"kind": "replica"}) != []
    assert check_stats_payload({"kind": "fleet", "ts": 1.0}) != []


# ------------------------------------------------------- run-scoped sink
def test_run_sink_roundtrip(tmp_path):
    d = str(tmp_path / "run")
    obs.start_run(d, meta={"config": "test"})
    obs.counter("train.steps").inc(10)
    obs.record_collective("all_reduce", 4096)
    with obs.span("step0"):
        pass
    obs.record_metrics(5, {"loss": 2.5, "steps_per_sec": 7.0})
    obs.end_run()
    assert not obs.enabled()

    recs = obs.read_metrics(os.path.join(d, "metrics.jsonl"))
    assert recs[0]["step"] == 5 and recs[0]["loss"] == 2.5
    spans = obs.read_metrics(os.path.join(d, "spans.jsonl"))
    assert [s["name"] for s in spans] == ["step0"]
    with open(os.path.join(d, "summary.json")) as f:
        summary = json.load(f)
    assert summary["schema_version"] == 1
    assert summary["counters"]["train.steps"] == 10
    assert summary["collectives"]["all_reduce"]["payload_bytes"] == 4096
    assert summary["histograms"]["metric.steps_per_sec"]["count"] == 1
    assert summary["run"]["config"] == "test"
    assert check_run_dir(d) == []  # the frozen schema accepts it


def test_run_dir_reuse_overwrites_previous_capture(tmp_path):
    """Retrying with the same --run-dir must not mix captures: start_run
    truncates the streams and drops any stale summary, so the dir always
    holds exactly one run."""
    d = str(tmp_path / "run")
    obs.start_run(d)
    obs.record_metrics(1, {"loss": 9.0})
    obs.end_run()
    obs.start_run(d)
    obs.record_metrics(1, {"loss": 1.0})
    with obs.span("only-run-2"):
        pass
    obs.end_run()
    recs = obs.read_metrics(os.path.join(d, "metrics.jsonl"))
    assert [r["loss"] for r in recs] == [1.0]
    spans = obs.read_metrics(os.path.join(d, "spans.jsonl"))
    assert [s["name"] for s in spans] == ["only-run-2"]


def test_start_run_resets_prior_instruments(tmp_path):
    obs.enable()
    obs.counter("stale").inc(3)
    obs.disable()
    obs.start_run(str(tmp_path / "r"))
    obs.end_run()
    with open(tmp_path / "r" / "summary.json") as f:
        assert "stale" not in json.load(f)["counters"]


def test_schema_checker_rejects_drift(tmp_path):
    d = str(tmp_path / "bad")
    os.makedirs(d)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"step": "four", "ts": 1.0}) + "\n")
    with open(os.path.join(d, "spans.jsonl"), "w") as f:
        f.write(json.dumps({"name": "x", "t0": 2.0, "t1": 1.0,
                            "dur_s": -1.0, "attrs": {}}) + "\n")
    with open(os.path.join(d, "summary.json"), "w") as f:
        json.dump({"schema_version": 2}, f)
    errors = check_run_dir(d)
    assert any("'step'" in e for e in errors)
    assert any("t1 < t0" in e for e in errors)
    assert any("schema_version" in e for e in errors)
    assert check_run_dir(str(tmp_path / "missing")) != []


# --------------------------------------- absorbed primitives (re-exports)
def test_metrics_logger_close_reopen(tmp_path):
    path = str(tmp_path / "m.jsonl")
    log = obs.MetricsLogger(path)
    log(1, {"a": 1})
    log.close()
    with pytest.raises(ValueError):
        log.log(2, {"a": 2})
    with obs.MetricsLogger(path) as log2:  # reopen appends
        log2(2, {"a": 2})
    assert [r["step"] for r in obs.read_metrics(path)] == [1, 2]


def test_utils_names_are_thin_reexports():
    from nezha_tpu import utils
    assert utils.MetricsLogger is obs.MetricsLogger
    assert utils.StepTimer is obs.StepTimer
    assert utils.Tracer is obs.Tracer


def test_step_timer_lap_windows():
    t = obs.StepTimer(window=4)
    assert t.lap(0.0, 5) is None  # no open window yet
    t.start()
    rate = t.lap(0.0, 10)
    assert rate is not None and rate > 0
    assert t.lap(0.0, 0) is None  # empty window -> no rate
    t.reset()
    assert t.lap(0.0, 3) is None  # reset forgets the window


def test_telemetry_json_recomputes_for_crashed_run(tmp_path, capsys):
    """A run that died before end_run() has only the JSONL streams;
    --json emits the summary recomputed from them, not null."""
    from nezha_tpu.cli.telemetry import main as telemetry_main
    d = str(tmp_path / "crashed")
    os.makedirs(d)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        f.write(json.dumps({"step": 1, "ts": 1.0, "loss": 2.0,
                            "steps_per_sec": 5.0}) + "\n")
        f.write(json.dumps({"step": 2, "ts": 2.0, "loss": 1.5,
                            "steps_per_sec": 7.0}) + "\n")
    with open(os.path.join(d, "spans.jsonl"), "w") as f:
        f.write(json.dumps({"name": "x", "t0": 0.0, "t1": 1.0,
                            "dur_s": 1.0, "attrs": {}}) + "\n")
    assert telemetry_main([d, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["recomputed"] is True
    assert out["histograms"]["metric.steps_per_sec"]["count"] == 2
    assert out["histograms"]["metric.loss"]["max"] == 2.0
    assert out["slowest_spans"][0]["name"] == "x"


def test_record_collective_bandwidth(tmp_path):
    obs.start_run(str(tmp_path / "bw"))
    obs.record_collective("all_reduce", 1 << 20, seconds=0.01,
                          bus_bytes=float(1 << 20))
    obs.end_run()
    with open(tmp_path / "bw" / "summary.json") as f:
        row = json.load(f)["collectives"]["all_reduce"]
    assert row["calls"] == 1 and row["payload_bytes"] == 1 << 20
    assert row["bus_gbps"]["count"] == 1
    assert row["bus_gbps"]["p50"] == pytest.approx((1 << 20) / 0.01 / 1e9)


def test_adopt_trace_header_rule():
    """THE shared header-adoption rule (obs.adopt_trace_header — one
    definition, used by all three HTTP front ends): the header fills an
    absent trace_id, never overrides a non-empty payload field, and
    leaves non-dict payloads for the caller's validation."""
    p = {"prompt_tokens": [1]}
    obs.adopt_trace_header({obs.TRACE_HEADER: "abc"}, p)
    assert p["trace_id"] == "abc"
    p = {"trace_id": "keep"}
    obs.adopt_trace_header({obs.TRACE_HEADER: "abc"}, p)
    assert p["trace_id"] == "keep"
    p = {}
    obs.adopt_trace_header({}, p)
    assert "trace_id" not in p
    obs.adopt_trace_header({obs.TRACE_HEADER: "abc"}, [1, 2])  # no-op
