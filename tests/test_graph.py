"""Graph IR tests: construction, interpretation, StableHLO lowering,
autograd, collective graph ops (SURVEY.md §0 north star)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nezha_tpu.graph import Graph, compile_graph, grad_callable, lower_stablehlo, to_callable


def _mlp_graph():
    g = Graph("mlp_fwd")
    x = g.placeholder((4, 8), name="x")
    w1 = g.placeholder((8, 16), name="w1")
    w2 = g.placeholder((16, 2), name="w2")
    h = g.relu(x @ w1)
    y = g.softmax(h @ w2)
    g.output(y)
    return g


def test_graph_interpret_matches_jnp():
    g = _mlp_graph()
    fn = to_callable(g)
    r = np.random.RandomState(0)
    x, w1, w2 = (r.randn(4, 8).astype(np.float32),
                 r.randn(8, 16).astype(np.float32),
                 r.randn(16, 2).astype(np.float32))
    y = fn(jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2))
    h = np.maximum(x @ w1, 0)
    logits = h @ w2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)


def test_graph_lowers_to_stablehlo():
    hlo = lower_stablehlo(_mlp_graph())
    assert "stablehlo.dot_general" in hlo or "stablehlo.dot" in hlo
    assert "stablehlo.maximum" in hlo  # the relu
    assert "func.func" in hlo


def test_graph_compiles_and_executes():
    g = _mlp_graph()
    compiled = compile_graph(g)
    y = compiled(jnp.ones((4, 8)), jnp.ones((8, 16)), jnp.ones((16, 2)))
    assert y.shape == (4, 2)
    np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), np.ones(4), rtol=1e-5)


def test_graph_autograd():
    g = Graph("quad")
    x = g.placeholder((3,), name="x")
    g.output(g.sum(x * x, axis=None, keepdims=False))
    dfn = grad_callable(g)
    gx = dfn(jnp.array([1.0, -2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(gx), [2.0, -4.0, 6.0], rtol=1e-6)


def test_graph_conv_and_layernorm():
    g = Graph("convnet")
    x = g.placeholder((1, 8, 8, 3), name="x")
    w = g.placeholder((3, 3, 3, 4), name="w")
    scale = g.placeholder((4,), name="scale")
    bias = g.placeholder((4,), name="bias")
    y = g.conv2d(x, w, stride=(2, 2))
    y = g.layernorm(y, scale, bias)
    g.output(y)
    fn = to_callable(g)
    out = fn(jnp.ones((1, 8, 8, 3)), jnp.ones((3, 3, 3, 4)),
             jnp.ones((4,)), jnp.zeros((4,)))
    assert out.shape == (1, 4, 4, 4)
    hlo = lower_stablehlo(g)
    assert "stablehlo.convolution" in hlo


def test_graph_collective_ops_lower(devices8):
    """Graph-level all_reduce lowers to a real XLA collective and runs."""
    from nezha_tpu.parallel import make_mesh
    from nezha_tpu.parallel._compat import shard_map

    g = Graph("dp_sum")
    x = g.placeholder((8,), name="x")
    g.output(g.all_reduce(x, axis_name="dp"))
    fn = to_callable(g)
    mesh = make_mesh({"dp": 8})
    mapped = shard_map(fn, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = jax.jit(mapped)(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_graph_repr():
    assert "matmul" in repr(_mlp_graph())
