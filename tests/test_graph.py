"""Graph IR tests: construction, interpretation, StableHLO lowering,
autograd, collective graph ops (SURVEY.md §0 north star)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nezha_tpu.graph import Graph, compile_graph, grad_callable, lower_stablehlo, to_callable
from nezha_tpu.graph import programs


def _mlp_graph():
    g = Graph("mlp_fwd")
    x = g.placeholder((4, 8), name="x")
    w1 = g.placeholder((8, 16), name="w1")
    w2 = g.placeholder((16, 2), name="w2")
    h = g.relu(x @ w1)
    y = g.softmax(h @ w2)
    g.output(y)
    return g


def test_graph_interpret_matches_jnp():
    g = _mlp_graph()
    fn = to_callable(g)
    r = np.random.RandomState(0)
    x, w1, w2 = (r.randn(4, 8).astype(np.float32),
                 r.randn(8, 16).astype(np.float32),
                 r.randn(16, 2).astype(np.float32))
    y = fn(jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2))
    h = np.maximum(x @ w1, 0)
    logits = h @ w2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)


def test_graph_lowers_to_stablehlo():
    hlo = lower_stablehlo(_mlp_graph())
    assert "stablehlo.dot_general" in hlo or "stablehlo.dot" in hlo
    assert "stablehlo.maximum" in hlo  # the relu
    assert "func.func" in hlo


def test_graph_compiles_and_executes():
    g = _mlp_graph()
    compiled = compile_graph(g)
    y = compiled(jnp.ones((4, 8)), jnp.ones((8, 16)), jnp.ones((16, 2)))
    assert y.shape == (4, 2)
    np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), np.ones(4), rtol=1e-5)


def test_graph_autograd():
    g = Graph("quad")
    x = g.placeholder((3,), name="x")
    g.output(g.sum(x * x, axis=None, keepdims=False))
    dfn = grad_callable(g)
    gx = dfn(jnp.array([1.0, -2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(gx), [2.0, -4.0, 6.0], rtol=1e-6)


def test_graph_conv_and_layernorm():
    g = Graph("convnet")
    x = g.placeholder((1, 8, 8, 3), name="x")
    w = g.placeholder((3, 3, 3, 4), name="w")
    scale = g.placeholder((4,), name="scale")
    bias = g.placeholder((4,), name="bias")
    y = g.conv2d(x, w, stride=(2, 2))
    y = g.layernorm(y, scale, bias)
    g.output(y)
    fn = to_callable(g)
    out = fn(jnp.ones((1, 8, 8, 3)), jnp.ones((3, 3, 3, 4)),
             jnp.ones((4,)), jnp.zeros((4,)))
    assert out.shape == (1, 4, 4, 4)
    hlo = lower_stablehlo(g)
    assert "stablehlo.convolution" in hlo


def test_graph_collective_ops_lower(devices8):
    """All three graph-level collectives lower to real XLA collectives and
    run: all_reduce sums across shards; reduce_scatter + all_gather round-
    trip a sharded vector (the ZeRO-1 wire pair, as IR nodes)."""
    from nezha_tpu.parallel import make_mesh
    from nezha_tpu.parallel._compat import shard_map

    mesh = make_mesh({"dp": 8})

    g = Graph("dp_sum")
    x = g.placeholder((8,), name="x")
    g.output(g.all_reduce(x, axis_name="dp"))
    fn = to_callable(g)
    mapped = shard_map(fn, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = jax.jit(mapped)(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    g2 = Graph("rs_ag")
    y = g2.placeholder((16,), name="y")  # per-shard rows
    g2.output(g2.all_gather(g2.reduce_scatter(y, axis_name="dp"),
                            axis_name="dp"))
    fn2 = to_callable(g2)
    mapped2 = shard_map(fn2, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp"))
    vals = jnp.tile(jnp.arange(16.0), 8)  # every shard holds arange(16)
    out2 = jax.jit(mapped2)(vals)
    # psum_scatter then all_gather == plain psum: each shard ends with the
    # full summed vector.
    np.testing.assert_allclose(np.asarray(out2),
                               jnp.tile(jnp.arange(16.0) * 8, 8))


def test_graph_dp_step_matches_single_graph(devices8):
    """The DP graph engine (VERDICT r3 missing #4: gradient all-reduce as an
    IR node, shard_map'd over dp=8) tracks the single-device graph engine
    step-for-step on the same global batch, and the collective genuinely
    lowers — the update graph's StableHLO contains a real all_reduce op."""
    from nezha_tpu import parallel
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.parallel._compat import shard_map

    dims, batch = [16, 32, 10], 16
    params = MLP(dims[0], (dims[1],), dims[2]).init(
        jax.random.PRNGKey(0))["params"]
    zeros = lambda: jax.tree_util.tree_map(np.zeros_like, params)
    ref_state = {"params": params, "vel": zeros()}
    mesh = parallel.make_mesh({"dp": 8})
    dp_state = parallel.replicate(
        mesh, {"params": jax.tree_util.tree_map(jnp.copy, params),
               "vel": zeros()})

    ref_step = programs.make_mlp_graph_train_step(dims, batch, lr=0.1)
    dp_step = programs.make_mlp_graph_dp_train_step(dims, batch, lr=0.1,
                                                    mesh=mesh)
    rng = np.random.RandomState(1)
    shard = programs.onehot_shard_fn(dims[-1])
    for _ in range(3):
        img = rng.rand(batch, dims[0]).astype(np.float32)
        labels = rng.randint(0, dims[-1], batch)
        b = shard({"image": img, "label": labels})
        ref_state, ref_m = ref_step(ref_state, b)
        dp_state, dp_m = dp_step(dp_state, parallel.shard_batch(mesh, b))
        np.testing.assert_allclose(float(dp_m["loss"]), float(ref_m["loss"]),
                                   rtol=1e-5, atol=1e-6)
    for (ka, a), (kb, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ref_state["params"]),
            jax.tree_util.tree_leaves_with_path(dp_state["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(ka))

    upd = to_callable(dp_step.update_graph)
    shape = tuple(dp_step.update_graph.nodes[0].attrs["shape"])
    mapped = shard_map(upd, mesh=mesh, in_specs=(P(), P(), P()),
                       out_specs=(P(), P()))
    arr = jnp.zeros(shape, jnp.float32)
    hlo = str(jax.jit(mapped).lower(arr, arr, arr).compiler_ir(
        dialect="stablehlo"))
    assert "all_reduce" in hlo  # the IR collective survives lowering


def test_graph_clip_matches_module():
    """The IR-authored global-norm clip (clip_scale_graph: min(1, C/(n+eps))
    via relu) tracks the module engine's with_grad_clipping step-for-step
    at a clip tight enough to actively bind."""
    from nezha_tpu import ops, optim
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.loop import init_train_state, make_train_step

    dims, batch, clip = [16, 32, 10], 16, 0.05
    model = MLP(dims[0], (dims[1],), dims[2])
    opt = optim.with_grad_clipping(optim.momentum(0.1, beta=0.9), clip)
    mstate = init_train_state(model, opt, jax.random.PRNGKey(0))
    mstep = make_train_step(
        model, opt,
        lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
            logits, b["label"]).mean(),
        donate=False)

    params0 = jax.tree_util.tree_map(
        jnp.copy, mstate["variables"]["params"])
    zeros = lambda: jax.tree_util.tree_map(np.zeros_like, params0)
    gstate = {"params": params0, "vel": zeros()}
    pstate = {"params": jax.tree_util.tree_map(jnp.copy, params0),
              "vel": zeros()}
    gstep = programs.make_mlp_graph_train_step(dims, batch, lr=0.1,
                                               clip_norm=clip)
    plain = programs.make_mlp_graph_train_step(dims, batch, lr=0.1)

    rng = np.random.RandomState(3)
    shard = programs.onehot_shard_fn(dims[-1])
    for _ in range(3):
        img = rng.rand(batch, dims[0]).astype(np.float32)
        labels = rng.randint(0, dims[-1], batch)
        mstate, _ = mstep(mstate, {"image": img, "label": labels})
        b = shard({"image": img, "label": labels})
        gstate, _ = gstep(gstate, b)
        pstate, _ = plain(pstate, b)

    for (ka, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(
                mstate["variables"]["params"]),
            jax.tree_util.tree_leaves_with_path(gstate["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(ka))
    # The clip actually bound (else the parity above is vacuous).
    diffs = [float(np.abs(np.asarray(a) - np.asarray(b_)).max())
             for (_, a), (_, b_) in zip(
                 jax.tree_util.tree_leaves_with_path(gstate["params"]),
                 jax.tree_util.tree_leaves_with_path(pstate["params"]))]
    assert max(diffs) > 1e-4, "clip never engaged; parity proves nothing"

    # Regression (r4 review): a huge clip_norm must be a no-op (scale
    # exactly 1.0) — the naive min(1,r) = r - relu(r-1) form collapses to
    # 0 in fp32 once r > 2^24, silently zeroing every gradient.
    g5 = np.full(4, 5.0, np.float32)  # norm 10
    fn = to_callable(programs.clip_scale_graph([(4,)], 1e9))
    assert float(fn(g5)) == 1.0
    fn_tight = to_callable(programs.clip_scale_graph([(4,)], 0.1))
    np.testing.assert_allclose(float(fn_tight(g5)), 0.01, rtol=1e-4)


def test_graph_resnet_dp_matches_single_on_replicated_shards(devices8):
    """The conv path through the IR-dp engine: with every dp shard fed
    IDENTICAL rows, per-shard BN batch stats equal the single-device ones
    and the all-reduce averages equal gradients — so the dp step must
    match the single-device graph step on the local batch EXACTLY. (With
    distinct rows, per-shard stats differ by design — standard DP-BN; see
    make_resnet_graph_dp_train_step.)"""
    from nezha_tpu import parallel
    from nezha_tpu.models.resnet import ResNet

    model = ResNet((1, 1), num_classes=10, in_channels=3)
    local, size, world = 2, 16, 8
    mesh = parallel.make_mesh({"dp": world})
    state = programs.init_graph_resnet_state(model, jax.random.PRNGKey(0))
    copy = lambda t: jax.tree_util.tree_map(np.copy, t)
    ref_state, dp_state = copy(state), parallel.replicate(mesh, copy(state))

    ref_step = programs.make_resnet_graph_train_step(model, lr=0.1)
    dp_step = programs.make_resnet_graph_dp_train_step(
        model, local * world, lr=0.1, mesh=mesh)

    rng = np.random.RandomState(5)
    for _ in range(2):
        img = rng.rand(local, size, size, 3).astype(np.float32)
        labels = rng.randint(0, 10, local).astype(np.int32)
        ref_state, ref_m = ref_step(ref_state,
                                    {"image": img, "labels": labels})
        gb = {"image": np.tile(img, (world, 1, 1, 1)),
              "labels": np.tile(labels, world)}
        dp_state, dp_m = dp_step(dp_state, parallel.shard_batch(mesh, gb))
        np.testing.assert_allclose(float(dp_m["loss"]), float(ref_m["loss"]),
                                   rtol=1e-5, atol=1e-6)
    for (ka, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ref_state["params"]),
            jax.tree_util.tree_leaves_with_path(dp_state["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(ka))


def test_graph_zero1_matches_single_graph(devices8):
    """ZeRO-1 authored in the IR (VERDICT r3 weak #3): gather/flatten/
    update programs whose all_gather + reduce_scatter are IR nodes,
    shard_map'd over dp=8, track the single-device graph engine
    step-for-step on the same global batch — and both wire collectives
    genuinely lower into the stablehlo."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu import parallel
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.parallel._compat import shard_map

    dims, batch = [16, 32, 10], 16
    mesh = parallel.make_mesh({"dp": 8})
    params = MLP(dims[0], (dims[1],), dims[2]).init(
        jax.random.PRNGKey(0))["params"]
    ref_state = {"params": params,
                 "vel": jax.tree_util.tree_map(np.zeros_like, params)}
    z_state = programs.init_graph_mlp_zero1_state(dims, jax.random.PRNGKey(0),
                                                  mesh)

    ref_step = programs.make_mlp_graph_train_step(dims, batch, lr=0.1)
    z_step = programs.make_mlp_graph_zero1_train_step(dims, batch, lr=0.1,
                                                      mesh=mesh)
    rng = np.random.RandomState(7)
    shard = programs.onehot_shard_fn(dims[-1])
    for _ in range(3):
        img = rng.rand(batch, dims[0]).astype(np.float32)
        labels = rng.randint(0, dims[-1], batch)
        b = shard({"image": img, "label": labels})
        ref_state, ref_m = ref_step(ref_state, b)
        z_state, z_m = z_step(z_state, parallel.shard_batch(mesh, b))
        np.testing.assert_allclose(float(z_m["loss"]), float(ref_m["loss"]),
                                   rtol=1e-5, atol=1e-6)

    z_params = programs.materialize_graph_zero1_params(dims, z_state)
    for (ka, a), (_, b_) in zip(
            jax.tree_util.tree_leaves_with_path(ref_state["params"]),
            jax.tree_util.tree_leaves_with_path(z_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=jax.tree_util.keystr(ka))

    # Both wire collectives survive lowering as stablehlo ops.
    upd = to_callable(z_step.update_graph)
    n_pad = z_step.update_graph.nodes[2].attrs["shape"][0]
    mapped = shard_map(upd, mesh=mesh,
                       in_specs=(P("dp"), P("dp"), P(None)),
                       out_specs=(P("dp"), P("dp")))
    hlo = str(jax.jit(mapped).lower(
        jnp.zeros(n_pad), jnp.zeros(n_pad),
        jnp.zeros(n_pad)).compiler_ir(dialect="stablehlo"))
    assert "reduce_scatter" in hlo
    gat = to_callable(z_step.gather_graph)
    mapped_g = shard_map(gat, mesh=mesh, in_specs=P("dp"),
                         out_specs=tuple(P() for _ in range(4)))
    hlo_g = str(jax.jit(mapped_g).lower(
        jnp.zeros(n_pad)).compiler_ir(dialect="stablehlo"))
    assert "all_gather" in hlo_g


def test_graph_dp_rejects_ragged_batch(devices8):
    from nezha_tpu import parallel
    import pytest
    mesh = parallel.make_mesh({"dp": 8})
    with pytest.raises(ValueError, match="not divisible"):
        programs.make_mlp_graph_dp_train_step([16, 32, 10], 12, lr=0.1,
                                              mesh=mesh)


def test_graph_repr():
    assert "matmul" in repr(_mlp_graph())


def test_graph_mlp_program_matches_module_forward():
    """IR-engine loss == Module-engine loss on identical params/batch
    (VERDICT round 1 item 6: the IR as a production path, with parity)."""
    from nezha_tpu import ops
    from nezha_tpu.graph import programs
    from nezha_tpu.models.mlp import MLP

    dims, batch = [784, 64, 32, 10], 8
    state = programs.init_graph_mlp_state(dims, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    img = rng.rand(batch, dims[0]).astype(np.float32)
    labels = rng.randint(0, dims[-1], batch)
    shard = programs.onehot_shard_fn(dims[-1])
    b = shard({"image": img, "label": labels})

    g = programs.mlp_loss_graph(dims, batch)
    flat = [state["params"][n][k]
            for n in ("fc0", "fc1", "head") for k in ("w", "b")]
    graph_loss = to_callable(g)(*flat, b["image"], b["onehot"])

    model = MLP(dims[0], tuple(dims[1:-1]), dims[-1])
    logits, _ = model.apply({"params": state["params"], "state": {}}, img)
    ref_loss = ops.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(float(graph_loss), float(ref_loss), rtol=1e-5)

    hlo = lower_stablehlo(g)
    assert "stablehlo.dot_general" in hlo  # the north-star lowering


def test_graph_mlp_program_trains():
    """The full IR train step (loss graph + grad + momentum-update graphs
    through the Executor) reduces the loss."""
    from nezha_tpu.graph import programs

    dims, batch = [16, 32, 10], 16
    step = programs.make_mlp_graph_train_step(dims, batch, lr=0.1)
    state = {"params": {"fc0": None, "head": None}, "vel": None}
    # init via the module-matched initializer at these dims
    from nezha_tpu.models.mlp import MLP
    import jax as _jax
    params = MLP(dims[0], (dims[1],), dims[2]).init(
        _jax.random.PRNGKey(0))["params"]
    state = {"params": params,
             "vel": _jax.tree_util.tree_map(np.zeros_like, params)}
    rng = np.random.RandomState(1)
    img = rng.rand(batch, dims[0]).astype(np.float32)
    labels = (img.sum(axis=1) * 3).astype(np.int64) % dims[-1]
    b = programs.onehot_shard_fn(dims[-1])({"image": img, "label": labels})
    losses = []
    for _ in range(40):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7
    assert step.executor.stats()["hits"] > 30  # compiled once, reused


def test_graph_pool_and_batchnorm_ops_match_nn():
    """The RN50-building-block ops (max/avg pool, training-mode batchnorm)
    lower to the same math as the nn layer implementations."""
    from nezha_tpu import nn as nzn
    from nezha_tpu.nn.layers import avg_pool, max_pool

    rng = np.random.RandomState(0)
    x = rng.rand(2, 8, 8, 4).astype(np.float32)
    sc = rng.rand(4).astype(np.float32)
    bi = rng.rand(4).astype(np.float32)

    g = Graph("pool_bn")
    xin = g.placeholder(x.shape)
    scin = g.placeholder(sc.shape)
    biin = g.placeholder(bi.shape)
    g.output(g.max_pool2d(xin, 3, 2, "SAME"),
             g.avg_pool2d(xin, 2, 2, "VALID"),
             g.batchnorm(xin, scin, biin))
    mp, ap, bn = to_callable(g)(x, sc, bi)

    np.testing.assert_allclose(np.asarray(mp), np.asarray(
        max_pool(jnp.asarray(x), 3, 2, "SAME")))
    np.testing.assert_allclose(np.asarray(ap), np.asarray(
        avg_pool(jnp.asarray(x), 2, 2, "VALID")))
    layer = nzn.BatchNorm(4)
    ref, _ = layer.apply(
        {"params": {"scale": jnp.asarray(sc), "bias": jnp.asarray(bi)},
         "state": {"mean": jnp.zeros(4), "var": jnp.ones(4)}},
        jnp.asarray(x), training=True)
    np.testing.assert_allclose(np.asarray(bn), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def _tiny_gpt2_module():
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    return GPT2(GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                           num_heads=2, hidden_size=32))


def test_graph_gpt2_forward_matches_module():
    """The IR-composed attention/block stack reproduces the module's loss
    (VERDICT r2 missing #6: the IR can now express a transformer)."""
    import jax as _jax

    from nezha_tpu.models.gpt2 import lm_loss

    model = _tiny_gpt2_module()
    variables = model.init(_jax.random.PRNGKey(0))
    toks = np.random.RandomState(1).randint(0, 128, (4, 17)).astype(np.int32)

    logits, _ = model.apply(variables, {"tokens": jnp.asarray(toks)})
    ref_loss = float(lm_loss(logits, {"tokens": jnp.asarray(toks)}))

    g = programs.gpt2_loss_graph(model.cfg, variables["params"],
                                 batch=4, seq=16)
    flat = _jax.tree_util.tree_leaves(variables["params"])
    graph_loss = float(to_callable(g)(*flat, toks[:, :-1],
                                      np.ascontiguousarray(toks[:, 1:])))
    np.testing.assert_allclose(graph_loss, ref_loss, rtol=1e-5)


def test_graph_gpt2_trains_and_matches_module_adamw():
    """3 steps of the IR GPT-2 program (IR forward + IR AdamW graphs) track
    the module engine + optim.adamw step-for-step."""
    import jax as _jax

    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import lm_loss
    from nezha_tpu.train.loop import init_train_state, make_train_step

    model = _tiny_gpt2_module()
    sched = lambda t: 1e-3
    ref_state = init_train_state(model, optim.adamw(1e-3, weight_decay=0.1),
                                 _jax.random.PRNGKey(0))
    ref_step = make_train_step(model, optim.adamw(1e-3, weight_decay=0.1),
                               lm_loss, donate=False)

    gstate = programs.init_graph_gpt2_state(model, _jax.random.PRNGKey(0))
    gstep = programs.make_gpt2_graph_train_step(model, sched,
                                                weight_decay=0.1)
    shard = programs.lm_shard_fn()

    rng = np.random.RandomState(2)
    for i in range(3):
        b = {"tokens": rng.randint(0, 128, (4, 17)).astype(np.int32)}
        ref_state, rm = ref_step(ref_state, {"tokens": jnp.asarray(b["tokens"])})
        gstate, gm = gstep(gstate, shard(b))
        np.testing.assert_allclose(float(gm["loss"]), float(rm["loss"]),
                                   rtol=2e-5, atol=1e-6)

    for (ka, a), (kb, bb) in zip(
            jax.tree_util.tree_leaves_with_path(
                ref_state["variables"]["params"]),
            jax.tree_util.tree_leaves_with_path(gstate["params"])):
        # Engines differ in fp32 reduction order (einsum vs composed
        # matmul) and pow(x,.5) vs sqrt; AdamW's early tiny-sqrt(nu)
        # denominators amplify that to ~5e-5 on isolated elements. Loss
        # parity above stays at 2e-5.
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-4,
                                   err_msg=jax.tree_util.keystr(ka))


def test_graph_gpt2_dp_matches_single_graph(devices8):
    """The AdamW configs through the IR-dp engine (dp_adamw_update_graph:
    all_reduce as an IR node): dp=8 tracks the single-device graph engine
    EXACTLY on the same global batch (no batch statistics in GPT-2, so
    mean-of-shard grads == global grads)."""
    import jax as _jax

    from nezha_tpu import parallel

    model = _tiny_gpt2_module()
    sched = lambda t: 1e-3
    mesh = parallel.make_mesh({"dp": 8})
    ref_state = programs.init_graph_gpt2_state(model, _jax.random.PRNGKey(0))
    dp_state = programs.init_graph_gpt2_state(model, _jax.random.PRNGKey(0))
    ref_step = programs.make_gpt2_graph_train_step(model, sched,
                                                   weight_decay=0.1)
    dp_step = programs.make_gpt2_graph_train_step(model, sched,
                                                  weight_decay=0.1,
                                                  mesh=mesh)
    shard = programs.lm_shard_fn()
    rng = np.random.RandomState(4)
    for _ in range(2):
        b = shard({"tokens": rng.randint(0, 128, (8, 17)).astype(np.int32)})
        ref_state, rm = ref_step(ref_state, b)
        dp_state, dm = dp_step(dp_state, parallel.shard_batch(mesh, b))
        np.testing.assert_allclose(float(dm["loss"]), float(rm["loss"]),
                                   rtol=1e-5, atol=1e-6)
    for (ka, a), (_, bb) in zip(
            jax.tree_util.tree_leaves_with_path(ref_state["params"]),
            jax.tree_util.tree_leaves_with_path(dp_state["params"])):
        # psum-then-scale vs single-reduction order differ at ~1e-8 fp32;
        # AdamW's early tiny-sqrt(nu) denominators amplify that on
        # near-zero gradient elements (same band as the module-parity
        # test above). Loss parity stays at 1e-5.
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=jax.tree_util.keystr(ka))


def test_graph_resnet_forward_matches_module():
    """The IR-composed bottleneck ResNet reproduces the module's training-
    mode loss (configs 2/5 expressible in the IR, VERDICT r2 missing #6)."""
    import jax as _jax

    from nezha_tpu.models.resnet import ResNet
    from nezha_tpu.ops import softmax_cross_entropy_with_integer_labels

    model = ResNet((1, 1), num_classes=10)
    variables = model.init(_jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    image = rng.rand(2, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, 2).astype(np.int32)

    logits, _ = model.apply(variables, {"image": jnp.asarray(image)},
                            training=True)
    ref = float(softmax_cross_entropy_with_integer_labels(
        logits, jnp.asarray(labels)))

    g = programs.resnet_loss_graph((1, 1), variables["params"],
                                   batch=2, size=32)
    flat = _jax.tree_util.tree_leaves(variables["params"])
    got = float(to_callable(g)(*flat, image, labels))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_graph_resnet_trains():
    """Full IR train step (IR forward + momentum update graphs): loss
    descends on a fixed batch."""
    import jax as _jax

    from nezha_tpu.models.resnet import ResNet

    model = ResNet((1, 1), num_classes=10)
    state = programs.init_graph_resnet_state(model, _jax.random.PRNGKey(0))
    step = programs.make_resnet_graph_train_step(model, lr=0.05)
    shard = programs.image_shard_fn()
    rng = np.random.RandomState(2)
    b = shard({"image": rng.rand(8, 32, 32, 3).astype(np.float32),
               "label": rng.randint(0, 10, 8)})
    losses = []
    for _ in range(8):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0] * 0.9


def test_graph_bert_forward_matches_module():
    """The IR-composed post-LN encoder + MLM head reproduces the module's
    masked loss — with this, ALL FIVE benchmark configs' models are
    expressible in the IR."""
    import jax as _jax

    from nezha_tpu import data
    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss

    model = Bert(BertConfig(vocab_size=128, max_positions=32, num_layers=2,
                            num_heads=2, hidden_size=32))
    variables = model.init(_jax.random.PRNGKey(0))
    b = next(data.synthetic_mlm_batches(4, seq_len=16, vocab_size=128,
                                        mask_token=1))

    logits, _ = model.apply(variables, {k: jnp.asarray(v)
                                        for k, v in b.items()})
    ref = float(mlm_loss(logits, {k: jnp.asarray(v) for k, v in b.items()}))

    g = programs.bert_loss_graph(model.cfg, variables["params"],
                                 batch=4, seq=16)
    feeds = programs.bert_shard_fn()(b)
    flat = _jax.tree_util.tree_leaves(variables["params"])
    got = float(to_callable(g)(
        *flat, feeds["tokens"], feeds["segment_ids"], feeds["attn_mask"],
        feeds["safe_labels"], feeds["label_mask"]))
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_graph_bert_trains():
    import jax as _jax

    from nezha_tpu import data
    from nezha_tpu.models.bert import Bert, BertConfig

    model = Bert(BertConfig(vocab_size=128, max_positions=32, num_layers=1,
                            num_heads=2, hidden_size=32))
    state = programs.init_graph_bert_state(model, _jax.random.PRNGKey(0))
    step = programs.make_bert_graph_train_step(model, lambda t: 1e-3)
    shard = programs.bert_shard_fn()
    b = shard(next(data.synthetic_mlm_batches(8, seq_len=16, vocab_size=128,
                                              mask_token=1)))
    losses = []
    for _ in range(5):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def _attn_graphs(impl):
    g = Graph(f"attn_{impl}")
    q = g.placeholder((2, 2, 16, 8), name="q")
    k = g.placeholder((2, 2, 16, 8), name="k")
    v = g.placeholder((2, 2, 16, 8), name="v")
    g.output(g.flash_attention(q, k, v, causal=True, impl=impl))
    return g


def test_graph_flash_attention_node_matches_composed():
    """The fused IR node (forced onto the Pallas kernel — interpret mode
    on CPU) matches the composed-XLA lowering, forward and gradient: the
    IR path can express the production attention (VERDICT r4 item 6)."""
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(2, 2, 16, 8).astype(np.float32) for _ in range(3))

    f_pallas = to_callable(_attn_graphs("pallas"))
    f_xla = to_callable(_attn_graphs("xla"))
    np.testing.assert_allclose(np.asarray(f_pallas(q, k, v)),
                               np.asarray(f_xla(q, k, v)),
                               rtol=5e-4, atol=5e-5)

    def loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v) ** 2)
        return inner

    gp = jax.grad(loss(f_pallas), argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss(f_xla), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_graph_flash_attention_node_lowers():
    """The node lowers to StableHLO (auto -> composed on CPU) and the
    graph repr carries it."""
    g = _attn_graphs("auto")
    hlo = lower_stablehlo(g)
    assert "stablehlo" in hlo
    assert "flash_attention" in repr(g)


def test_graph_gpt2_flash_node_matches_composed_program():
    """gpt2_loss_graph with attn_impl='auto' (flash node) reproduces the
    attn_impl='xla' fully-composed program's loss AND its gradients."""
    import dataclasses as _dc

    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                     num_heads=2, hidden_size=32)
    model = GPT2(cfg)
    variables = model.init(jax.random.PRNGKey(0))
    toks = np.random.RandomState(1).randint(0, 128, (4, 17)).astype(np.int32)
    flat = jax.tree_util.tree_leaves(variables["params"])
    args = (*flat, toks[:, :-1], np.ascontiguousarray(toks[:, 1:]))

    g_flash = programs.gpt2_loss_graph(cfg, variables["params"],
                                       batch=4, seq=16)
    assert any(n.op == "flash_attention" for n in g_flash.nodes)
    g_comp = programs.gpt2_loss_graph(
        _dc.replace(cfg, attn_impl="xla"), variables["params"],
        batch=4, seq=16)
    assert not any(n.op == "flash_attention" for n in g_comp.nodes)

    f1, f2 = to_callable(g_flash), to_callable(g_comp)
    np.testing.assert_allclose(float(f1(*args)), float(f2(*args)),
                               rtol=1e-5)
    n = len(flat)
    g1 = jax.grad(lambda *a: f1(*a), argnums=tuple(range(n)))(*args)
    g2 = jax.grad(lambda *a: f2(*a), argnums=tuple(range(n)))(*args)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_graph_gpt2_bf16_policy_tracks_fp32():
    """compute_dtype='bfloat16' authors the module bf16 policy in the IR:
    same init, losses track the fp32 program within bf16 tolerance over 3
    IR-AdamW steps, and the graph really computes in bf16 (loss differs
    at fp32-exact tolerance)."""
    import jax as _jax
    import numpy as np

    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    cfg = GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                     num_heads=2, hidden_size=32)
    model = GPT2(cfg)
    toks = np.random.RandomState(1).randint(0, 128, (4, 17)).astype(np.int32)
    batch = {"tokens": toks}

    def run(compute_dtype):
        state = programs.init_graph_gpt2_state(model, _jax.random.PRNGKey(0))
        step = programs.make_gpt2_graph_train_step(
            model, lambda t: 1e-3, compute_dtype=compute_dtype)
        shard = programs.lm_shard_fn()
        losses = []
        for _ in range(3):
            state, m = step(state, shard(batch))
            losses.append(float(m["loss"]))
        return losses

    l32 = run("float32")
    l16 = run("bfloat16")
    np.testing.assert_allclose(l16, l32, rtol=2e-2)  # tracks
    assert not np.allclose(l16, l32, rtol=1e-6)      # but really bf16
