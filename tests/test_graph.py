"""Graph IR tests: construction, interpretation, StableHLO lowering,
autograd, collective graph ops (SURVEY.md §0 north star)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nezha_tpu.graph import Graph, compile_graph, grad_callable, lower_stablehlo, to_callable


def _mlp_graph():
    g = Graph("mlp_fwd")
    x = g.placeholder((4, 8), name="x")
    w1 = g.placeholder((8, 16), name="w1")
    w2 = g.placeholder((16, 2), name="w2")
    h = g.relu(x @ w1)
    y = g.softmax(h @ w2)
    g.output(y)
    return g


def test_graph_interpret_matches_jnp():
    g = _mlp_graph()
    fn = to_callable(g)
    r = np.random.RandomState(0)
    x, w1, w2 = (r.randn(4, 8).astype(np.float32),
                 r.randn(8, 16).astype(np.float32),
                 r.randn(16, 2).astype(np.float32))
    y = fn(jnp.asarray(x), jnp.asarray(w1), jnp.asarray(w2))
    h = np.maximum(x @ w1, 0)
    logits = h @ w2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5)


def test_graph_lowers_to_stablehlo():
    hlo = lower_stablehlo(_mlp_graph())
    assert "stablehlo.dot_general" in hlo or "stablehlo.dot" in hlo
    assert "stablehlo.maximum" in hlo  # the relu
    assert "func.func" in hlo


def test_graph_compiles_and_executes():
    g = _mlp_graph()
    compiled = compile_graph(g)
    y = compiled(jnp.ones((4, 8)), jnp.ones((8, 16)), jnp.ones((16, 2)))
    assert y.shape == (4, 2)
    np.testing.assert_allclose(np.asarray(jnp.sum(y, -1)), np.ones(4), rtol=1e-5)


def test_graph_autograd():
    g = Graph("quad")
    x = g.placeholder((3,), name="x")
    g.output(g.sum(x * x, axis=None, keepdims=False))
    dfn = grad_callable(g)
    gx = dfn(jnp.array([1.0, -2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(gx), [2.0, -4.0, 6.0], rtol=1e-6)


def test_graph_conv_and_layernorm():
    g = Graph("convnet")
    x = g.placeholder((1, 8, 8, 3), name="x")
    w = g.placeholder((3, 3, 3, 4), name="w")
    scale = g.placeholder((4,), name="scale")
    bias = g.placeholder((4,), name="bias")
    y = g.conv2d(x, w, stride=(2, 2))
    y = g.layernorm(y, scale, bias)
    g.output(y)
    fn = to_callable(g)
    out = fn(jnp.ones((1, 8, 8, 3)), jnp.ones((3, 3, 3, 4)),
             jnp.ones((4,)), jnp.zeros((4,)))
    assert out.shape == (1, 4, 4, 4)
    hlo = lower_stablehlo(g)
    assert "stablehlo.convolution" in hlo


def test_graph_collective_ops_lower(devices8):
    """Graph-level all_reduce lowers to a real XLA collective and runs."""
    from nezha_tpu.parallel import make_mesh
    from nezha_tpu.parallel._compat import shard_map

    g = Graph("dp_sum")
    x = g.placeholder((8,), name="x")
    g.output(g.all_reduce(x, axis_name="dp"))
    fn = to_callable(g)
    mesh = make_mesh({"dp": 8})
    mapped = shard_map(fn, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    out = jax.jit(mapped)(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_graph_repr():
    assert "matmul" in repr(_mlp_graph())


def test_graph_mlp_program_matches_module_forward():
    """IR-engine loss == Module-engine loss on identical params/batch
    (VERDICT round 1 item 6: the IR as a production path, with parity)."""
    from nezha_tpu import ops
    from nezha_tpu.graph import programs
    from nezha_tpu.models.mlp import MLP

    dims, batch = [784, 64, 32, 10], 8
    state = programs.init_graph_mlp_state(dims, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    img = rng.rand(batch, dims[0]).astype(np.float32)
    labels = rng.randint(0, dims[-1], batch)
    shard = programs.onehot_shard_fn(dims[-1])
    b = shard({"image": img, "label": labels})

    g = programs.mlp_loss_graph(dims, batch)
    flat = [state["params"][n][k]
            for n in ("fc0", "fc1", "head") for k in ("w", "b")]
    graph_loss = to_callable(g)(*flat, b["image"], b["onehot"])

    model = MLP(dims[0], tuple(dims[1:-1]), dims[-1])
    logits, _ = model.apply({"params": state["params"], "state": {}}, img)
    ref_loss = ops.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(float(graph_loss), float(ref_loss), rtol=1e-5)

    hlo = lower_stablehlo(g)
    assert "stablehlo.dot_general" in hlo  # the north-star lowering


def test_graph_mlp_program_trains():
    """The full IR train step (loss graph + grad + momentum-update graphs
    through the Executor) reduces the loss."""
    from nezha_tpu.graph import programs

    dims, batch = [16, 32, 10], 16
    step = programs.make_mlp_graph_train_step(dims, batch, lr=0.1)
    state = {"params": {"fc0": None, "head": None}, "vel": None}
    # init via the module-matched initializer at these dims
    from nezha_tpu.models.mlp import MLP
    import jax as _jax
    params = MLP(dims[0], (dims[1],), dims[2]).init(
        _jax.random.PRNGKey(0))["params"]
    state = {"params": params,
             "vel": _jax.tree_util.tree_map(np.zeros_like, params)}
    rng = np.random.RandomState(1)
    img = rng.rand(batch, dims[0]).astype(np.float32)
    labels = (img.sum(axis=1) * 3).astype(np.int64) % dims[-1]
    b = programs.onehot_shard_fn(dims[-1])({"image": img, "label": labels})
    losses = []
    for _ in range(40):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.7
    assert step.executor.stats()["hits"] > 30  # compiled once, reused
