"""Collective bus-bandwidth harness runs on the virtual mesh and reports
sane records (correct collectives are covered by tests/test_parallel.py;
this validates the measurement plumbing)."""

import sys


def test_collectives_bench_runs():
    sys.path.insert(0, "benchmarks")
    try:
        import collectives
    finally:
        sys.path.pop(0)
    recs = collectives.run(sizes_mb=[0.25], iters=2)
    names = {r["collective"] for r in recs}
    assert names == {"all_reduce", "all_gather", "reduce_scatter",
                     "ppermute", "all_reduce_int8"}
    for r in recs:
        assert r["devices"] == 8
        assert r["time_ms"] > 0
        assert r["bus_gbps"] > 0
