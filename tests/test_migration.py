"""Disaggregated prefill/decode tiers with fault-tolerant paged-block
migration (the ISSUE 11 acceptance suite).

Layers under test, bottom up: the int8+scales wire codec
(serve/migrate.py), pool-level block export/install (ref == 1 writes,
trie registration), the scheduler's park/export/ack/resume lifecycle
(two-phase handoff with a TTL backstop), the router's disaggregated
pipeline over role-tagged replicas (admission -> migrate -> decode,
bounded seeded-backoff retries, local-decode degradation), and the
chaos acceptance: SIGKILL a prefill replica mid-migration under load
and prove zero silently-lost requests, zero block/scale leaks on BOTH
pools (the ``leak_check`` oracle runs on every surviving replica after
every drill), and the frozen program contract on every engine. Fault
points drilled here: ``router.migrate``, ``replica.kv_export``,
``replica.kv_install`` (plus ``serve.kv.bind`` via install exhaustion).
"""

import json
import os
import sys
import threading
import time

import pytest

import jax

from nezha_tpu import faults, obs
from nezha_tpu.faults import FaultPlan
from nezha_tpu.serve import (Engine, FinishReason, MigrationError,
                             Request, Scheduler, ServeConfig, migrate)
from nezha_tpu.serve.router import Router, register_router_instruments
from nezha_tpu.serve.supervisor import (RouterConfig, Supervisor,
                                        ThreadBackend)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_model():
    from nezha_tpu.cli.train import TINY_GPT2_KW
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    model = GPT2(GPT2Config(**TINY_GPT2_KW))
    return model, model.init(jax.random.PRNGKey(0))


def _engine(tiny_model, **kw):
    model, variables = tiny_model
    base = dict(max_batch_size=2, max_len=64, max_prefill_len=16,
                kv_block_size=8, queue_capacity=8)
    base.update(kw)
    return Engine(model, variables, ServeConfig(**base))


def _prompt(n, vocab=512, salt=0):
    return [(7 * i + 3 + 11 * salt) % vocab for i in range(n)]


# ----------------------------------------------------------- wire codec
def test_wire_codec_roundtrip_and_validation(tiny_model):
    import numpy as np
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    prompt = _prompt(21)
    sched.submit(Request(prompt=prompt, max_new_tokens=4,
                         request_id="w", prefill_only=True))
    sched.run_until_idle()
    wire = sched.export_parked("w")
    assert wire["nblocks"] == 2 and wire["block_size"] == 8
    tokens, layers, nbytes = migrate.decode_wire(wire)
    assert tokens == prompt[:16]
    assert nbytes == wire["nbytes"] > 0
    assert layers[0]["k"].dtype == np.int8
    assert layers[0]["k_scale"].dtype == np.float32
    # corrupt geometry fails typed, before any pool state is touched
    bad = dict(wire, nblocks=3)
    with pytest.raises(MigrationError):
        migrate.decode_wire(bad)
    with pytest.raises(MigrationError):
        migrate.decode_wire({"v": 99})
    sched.ack_parked("w")
    eng.pool.leak_check()


# ------------------------------------------------- scheduler lifecycle
def test_park_export_install_ack_bf16(tiny_model):
    """The two-phase handoff at scheduler level: park on A, pull into
    B's prefix cache, ACK releases A — leak_check clean on BOTH pools,
    and B's admission takes prefix-cache references (a genuine hit)."""
    a, b = _engine(tiny_model), _engine(tiny_model)
    sa, sb = Scheduler(a), Scheduler(b)
    prompt = _prompt(21)
    sa.submit(Request(prompt=prompt, max_new_tokens=6,
                      request_id="m", prefill_only=True))
    sa.run_until_idle()
    assert sa.results["m"].finish_reason == FinishReason.PREFILLED
    assert sa.parked_count == 1
    tokens, layers, nbytes = migrate.decode_wire(sa.export_parked("m"))
    assert sb.install_migrated(tokens, layers, nbytes) == 2
    assert sa.ack_parked("m") is True
    assert sa.ack_parked("m") is False          # idempotent, no double free
    assert sa.parked_count == 0
    a.pool.leak_check()
    sb.submit(Request(prompt=prompt, max_new_tokens=6, request_id="m"))
    sb.run_until_idle()
    res = sb.results["m"]
    assert res.finish_reason == "length" and len(res.tokens) == 6
    assert b.pool.prefix_hits == 1
    b.pool.leak_check()


def test_int8_migration_is_bit_identical(tiny_model):
    """int8 pools ship their blocks verbatim (the wire IS the storage
    format), so a migrated request's greedy decode matches a local
    int8 decode token for token."""
    kw = dict(kv_dtype="int8")
    src, dst, ref = (_engine(tiny_model, **kw) for _ in range(3))
    ss, sd, sr = Scheduler(src), Scheduler(dst), Scheduler(ref)
    prompt = _prompt(29)
    sr.submit(Request(prompt=prompt, max_new_tokens=8, request_id="r"))
    sr.run_until_idle()
    ss.submit(Request(prompt=prompt, max_new_tokens=8,
                      request_id="p", prefill_only=True))
    ss.run_until_idle()
    tokens, layers, nbytes = migrate.decode_wire(ss.export_parked("p"))
    sd.install_migrated(tokens, layers, nbytes)
    ss.ack_parked("p")
    sd.submit(Request(prompt=prompt, max_new_tokens=8, request_id="p"))
    sd.run_until_idle()
    assert sd.results["p"].tokens == sr.results["r"].tokens
    src.pool.leak_check()
    dst.pool.leak_check()


def test_resume_parked_local_decode(tiny_model):
    """The role=both degradation: a parked request resumes and decodes
    locally on its source — same result shape, no leak."""
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sched.submit(Request(prompt=_prompt(21), max_new_tokens=6,
                         request_id="loc", prefill_only=True))
    sched.run_until_idle()
    assert sched.resume_parked("loc") is True
    assert sched.resume_parked("loc") is False
    sched.run_until_idle()
    res = sched.results["loc"]
    assert res.finish_reason == "length" and len(res.tokens) == 6
    assert sched.parked_count == 0
    eng.pool.leak_check()


def test_parked_ttl_expiry_frees_blocks(tiny_model):
    """The leak-proofing backstop: a park nobody pulls, ACKs, or
    resumes (decode replica died post-pull, ACK lost on the wire) is
    reclaimed at its TTL — blocks return to the pool."""
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sched.parked_ttl_s = 0.02
    sched.submit(Request(prompt=_prompt(21), max_new_tokens=4,
                         request_id="exp", prefill_only=True))
    sched.run_until_idle()
    assert sched.parked_count == 1
    time.sleep(0.05)
    sched.step()
    assert sched.parked_count == 0
    with pytest.raises(KeyError):
        sched.export_parked("exp")
    eng.pool.leak_check()
    # every remaining block is held by the prefix cache alone (the
    # prompt's full blocks stay cached, evictable — not a leak)
    assert eng.pool.blocks_used == eng.pool.trie_only_blocks


def test_cancel_remaining_sweeps_parked(tiny_model):
    """Drain sweeps parked migrations: a drained source stops being
    pullable (typed 404 at the router's next /kv_export) and leaks
    nothing."""
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sched.submit(Request(prompt=_prompt(21), max_new_tokens=4,
                         request_id="d", prefill_only=True))
    sched.run_until_idle()
    assert sched.parked_count == 1
    sched.cancel_remaining()
    assert sched.parked_count == 0
    eng.pool.leak_check()
    # every remaining block is held by the prefix cache alone (the
    # prompt's full blocks stay cached, evictable — not a leak)
    assert eng.pool.blocks_used == eng.pool.trie_only_blocks


def test_install_exhaustion_is_typed_and_leak_free(tiny_model):
    """An install the destination pool cannot hold raises the typed
    KVBlocksExhausted (wrapped as MigrationError by the pull client)
    and releases every block it allocated — the retryable-failure half
    of the crash-leaves-one-owner contract."""
    from nezha_tpu.serve.slots import KVBlocksExhausted
    src = _engine(tiny_model)
    # destination with almost no blocks (1 scratch + 2 usable)
    dst = _engine(tiny_model, kv_num_blocks=3)
    ss, sd = Scheduler(src), Scheduler(dst)
    prompt = _prompt(33)                        # 4 full blocks of 8
    ss.submit(Request(prompt=prompt, max_new_tokens=4,
                      request_id="x", prefill_only=True))
    ss.run_until_idle()
    tokens, layers, nbytes = migrate.decode_wire(ss.export_parked("x"))
    with pytest.raises(KVBlocksExhausted):
        sd.install_migrated(tokens, layers, nbytes)
    dst.pool.leak_check()
    assert dst.pool.blocks_used == 0            # partial alloc released
    ss.ack_parked("x")
    src.pool.leak_check()


# --------------------------------------------------- router, role-aware
def _worker_args(extra=()):
    from nezha_tpu.cli.serve import build_parser
    return build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "64", "--max-prefill-len", "8",
         "--kv-block-size", "8", "--queue-capacity", "8",
         "--platform", "cpu", *extra])


def _cfg(**kw):
    base = dict(replicas=2, roles=("prefill", "decode"),
                probe_interval_s=0.1, probe_misses=3, route_retries=2,
                retry_backoff_base_s=0.01, retry_backoff_max_s=0.05,
                restart_backoff_base_s=0.05, restart_backoff_max_s=0.5,
                drain_timeout_s=20.0, seed=0)
    base.update(kw)
    return RouterConfig(**base)


def _cluster(cfg):
    sup = Supervisor(ThreadBackend(_worker_args(), drain_timeout_s=20.0,
                                   roles=cfg.roles), cfg)
    router = Router(sup, cfg)
    sup.start()
    assert router.wait_live(cfg.replicas, timeout_s=600), sup.describe()
    return sup, router


def _worker_sched(sup, rid):
    return sup.replicas()[rid].handle.worker._sched


def _leak_check_all(sup):
    """The both-pools oracle: every live replica's pool balances its
    ref-count books and holds no parked leftovers once traffic ends."""
    for r in sup.replicas():
        worker = getattr(r.handle, "worker", None)
        if worker is None or worker.dead.is_set():
            continue
        sched = worker._sched
        sched.engine.pool.leak_check()


def test_roles_config_validation():
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, roles=("prefill",))
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, roles=("prefill", "chef"))
    with pytest.raises(ValueError):
        RouterConfig(replicas=2, roles=("prefill", "prefill"))
    cfg = RouterConfig(replicas=2, roles=("prefill", "decode"))
    assert cfg.disaggregated and cfg.role_of(1) == "decode"
    assert not RouterConfig(replicas=2).disaggregated


@pytest.fixture(scope="module")
def disagg2(tiny_model):
    """1 prefill + 1 decode thread-hosted replicas + router (module
    scoped; chaos tests that consume clusters build their own)."""
    cfg = _cfg()
    sup, router = _cluster(cfg)
    yield sup, router
    router.stop()
    sup.shutdown()


def test_disaggregated_route_end_to_end(disagg2):
    """Admission lands on the prefill tier, the prompt's KV migrates
    over the int8 wire, the decode replica answers — and the response
    carries the migration meta (bytes, queueing split)."""
    sup, router = disagg2
    assert router.wait_live(2, timeout_s=600)
    assert [r["role"] for r in sup.describe()] == ["prefill", "decode"]
    migrations0 = router.migrations
    for i in range(3):
        code, obj = router.route(
            {"id": f"e2e-{i}", "prompt_tokens": _prompt(21, salt=i),
             "max_new_tokens": 5})
        assert code == 200, obj
        assert obj["finish_reason"] == "length"
        assert len(obj["tokens"]) == 5
        mig = obj["migration"]
        assert mig["bytes"] > 0 and mig["blocks"] == 2
        assert mig["acked"] is True
        assert mig["prefill_wait_s"] >= 0
        assert mig["decode_wait_s"] >= 0
    assert router.migrations == migrations0 + 3
    # the decode tier did the decoding: its pool saw the prefix hits
    assert _worker_sched(sup, 1).engine.pool.prefix_hits >= 3
    # two-phase handoff completed: nothing left parked anywhere
    for rid in (0, 1):
        assert _worker_sched(sup, rid).parked_count == 0
    _leak_check_all(sup)


def test_healthz_reports_role_and_parked(disagg2):
    import urllib.request
    sup, router = disagg2
    assert router.wait_live(2, timeout_s=600)
    r0 = sup.replicas()[0]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{r0.port}/healthz", timeout=10) as resp:
        obj = json.loads(resp.read())
    assert obj["role"] == "prefill" and obj["parked"] == 0


def test_router_migrate_fault_is_typed(disagg2):
    """The router.migrate fault point: chaos at the orchestrator itself
    surfaces as the typed injected_fault response, never a dropped
    request; the next request sails through."""
    sup, router = disagg2
    assert router.wait_live(2, timeout_s=600)
    faults.install(FaultPlan.parse("router.migrate:error@1"))
    code, obj = router.route({"id": "rm", "prompt_tokens": _prompt(21),
                              "max_new_tokens": 2})
    assert code == 500 and obj["error_type"] == "injected_fault"
    faults.clear()
    code, obj = router.route({"id": "rm2", "prompt_tokens": _prompt(21),
                              "max_new_tokens": 2})
    assert code == 200, obj
    _leak_check_all(sup)


def test_export_install_faults_retry_to_success(disagg2):
    """replica.kv_export / replica.kv_install drills: a one-shot
    injected failure on either side of the pull surfaces as the typed
    424 the router retries on — the request still finishes 200 and
    neither pool leaks."""
    sup, router = disagg2
    assert router.wait_live(2, timeout_s=600)
    for point in ("replica.kv_export", "replica.kv_install"):
        faults.install(FaultPlan.parse(f"{point}:error@1"))
        retries0 = router.retries + router.migrate_fallbacks
        code, obj = router.route(
            {"id": f"f-{point}", "prompt_tokens": _prompt(21, salt=7),
             "max_new_tokens": 3})
        assert code == 200, (point, obj)
        assert faults.active().injected_counts.get(point) == 1
        # the failure was absorbed by a retry or the local fallback
        assert router.retries + router.migrate_fallbacks > retries0
        faults.clear()
        for rid in (0, 1):
            assert _worker_sched(sup, rid).parked_count == 0
    _leak_check_all(sup)


def test_pull_of_lost_park_is_typed_park_lost(disagg2):
    """A live source whose park is GONE (acked away / TTL / drain)
    answers the pull with 404; the client raises the distinct
    ``park_lost`` kind — the router's restart-immediately signal (no
    doomed sweep of the decode tier)."""
    import urllib.request
    sup, router = disagg2
    assert router.wait_live(2, timeout_s=600)
    port = sup.replicas()[0].port
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps({"id": "gone", "prompt_tokens": _prompt(21),
                         "max_new_tokens": 4,
                         "prefill_only": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        assert json.loads(resp.read())["finish_reason"] == "prefilled"
    sched0 = _worker_sched(sup, 0)
    assert sched0.ack_parked("gone") is True      # park released
    dst = _worker_sched(sup, 1)
    with pytest.raises(MigrationError) as ei:
        migrate.pull_into(dst, {"port": port, "request_id": "gone"})
    assert ei.value.kind == "park_lost"
    _leak_check_all(sup)


def test_empty_install_does_not_count_a_migration(tiny_model):
    """serve.kv.migrations_total counts COMMITTED installs: an empty
    sub-block payload (or an already-cached prefix) increments
    nothing."""
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sub = _prompt(5)                   # shorter than one 8-token block
    sched.submit(Request(prompt=sub, max_new_tokens=2,
                         request_id="tiny", prefill_only=True))
    sched.run_until_idle()
    wire = sched.export_parked("tiny")
    assert wire["nblocks"] == 0
    dst = _engine(tiny_model)
    sd = Scheduler(dst)
    run_dir_ctr = obs.counter("serve.kv.migrations_total")
    tokens, layers, nbytes = migrate.decode_wire(wire)
    assert sd.install_migrated(tokens, layers, nbytes) == 0
    # no telemetry run is active here, so assert via a second install
    # of a REAL payload double-counting nothing: install the same
    # full-block payload twice — only the first counts.
    sched.ack_parked("tiny")
    sched.submit(Request(prompt=_prompt(21), max_new_tokens=2,
                         request_id="full", prefill_only=True))
    sched.run_until_idle()
    tokens, layers, nbytes = migrate.decode_wire(
        sched.export_parked("full"))
    assert sd.install_migrated(tokens, layers, nbytes) == 2
    assert sd.install_migrated(tokens, layers, nbytes) == 0  # cached
    sched.ack_parked("full")
    eng.pool.leak_check()
    dst.pool.leak_check()
    del run_dir_ctr


def test_no_live_decode_tier_degrades_to_local_decode(tiny_model):
    """Zero live decode replicas: the router falls back to LOCAL decode
    on the prefill replica (resume — the role=both degradation),
    counted in router.migrate_fallbacks_total, and the request still
    answers 200."""
    cfg = _cfg(restart_backoff_base_s=60.0, restart_backoff_max_s=120.0)
    sup, router = _cluster(cfg)
    try:
        sup.kill(1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and any(
                r.rid == 1 for r in sup.live_replicas()):
            router.probe_all()
            time.sleep(0.02)
        assert all(r.rid != 1 for r in sup.live_replicas())
        fallbacks0 = router.migrate_fallbacks
        code, obj = router.route(
            {"id": "deg", "prompt_tokens": _prompt(21),
             "max_new_tokens": 4})
        assert code == 200, obj
        assert obj.get("resumed") is True
        assert obj["migration"]["fallback"] == "no live decode replica"
        assert router.migrate_fallbacks == fallbacks0 + 1
        sched = _worker_sched(sup, 0)
        assert sched.parked_count == 0
        sched.engine.pool.leak_check()
    finally:
        router.stop()
        sup.shutdown()


def test_prefill_kill_mid_migration_chaos(tiny_model, tmp_path):
    """THE acceptance drill: 2 prefill + 1 decode replicas under
    concurrent load while the prefill tier is killed MID-TRANSFER
    (slowed exports guarantee in-flight migrations at the kill). Every
    request gets exactly one answer — 200 or a typed error — zero
    silently lost; the killed member restarts; leak_check passes on
    every surviving pool (source AND destination); the frozen program
    contract holds on every engine; and the run-dir record carrying
    the migration instruments is schema-valid."""
    import random

    cfg = _cfg(replicas=3, roles=("prefill", "prefill", "decode"),
               drain_timeout_s=20.0)
    sup, router = _cluster(cfg)
    run_dir = str(tmp_path / "mig_chaos")
    obs.start_run(run_dir, meta={"kind": "migration_chaos_test"})
    register_router_instruments()
    from nezha_tpu.serve.scheduler import register_serve_instruments
    register_serve_instruments()
    # Slow the export so the seeded kill provably lands mid-transfer.
    faults.install(FaultPlan.parse("replica.kv_export:delay=0.05x*"))
    try:
        N = 18
        results = []
        lock = threading.Lock()
        next_idx = {"n": 0}

        def client():
            while True:
                with lock:
                    i = next_idx["n"]
                    if i >= N:
                        return
                    next_idx["n"] += 1
                code, obj = router.route(
                    {"id": f"mc-{i}", "prompt_tokens": _prompt(21, salt=i),
                     "max_new_tokens": 4, "seed": i})
                with lock:
                    results.append((i, code, obj))

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        # Kill a prefill replica once a third of the load has answered
        # — exports are slowed, so migrations are in flight.
        krng = random.Random(11)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= N // 3:
                    break
            time.sleep(0.005)
        live_prefill = [r for r in sup.live_replicas()
                        if r.role == "prefill"]
        assert live_prefill
        sup.kill(live_prefill[krng.randrange(len(live_prefill))].rid)
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads)

        # Zero silently-lost: one answer per request, typed or 200.
        assert sorted(i for i, _, _ in results) == list(range(N))
        typed = {"no_live_replicas", "queue_full", "replica_lost",
                 "replica_timeout", "injected_fault", "migration_failed"}
        for i, code, obj in results:
            if code == 200:
                assert obj["finish_reason"] in ("length", "eos"), obj
            else:
                assert obj.get("error_type") in typed, (code, obj)
        assert router.migrations >= 1      # the tier genuinely migrated
        assert router.wait_live(3, timeout_s=600), sup.describe()

        # Both-pools leak oracle + frozen program contract on every
        # surviving engine (parks drain via ack/resume or the sweep).
        faults.clear()
        for r in sup.replicas():
            worker = getattr(r.handle, "worker", None)
            if worker is None or worker.dead.is_set():
                continue
            sched = worker._sched
            deadline = time.monotonic() + 90
            while sched.parked_count and time.monotonic() < deadline:
                time.sleep(0.05)
            if sched.parked_count:
                # a park whose puller died rides out its TTL; reclaim
                # deterministically rather than waiting a minute
                sched.parked_ttl_s = 0.0
                sched.step()
            assert sched.parked_count == 0
            sched.engine.pool.leak_check()
            stats = sched.engine.compile_stats()
            buckets = len(sched.engine.cfg.prefill_buckets)
            assert stats["entries"] <= 1 + buckets, stats
    finally:
        faults.clear()
        obs.end_run()
        router.stop()
        sup.shutdown()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    for name in ("serve.kv.migrations_total", "serve.kv.migration_bytes",
                 "router.migrate_fallbacks_total"):
        assert name in summary["counters"], name
    assert summary["counters"]["serve.kv.migrations_total"] >= 1
    for name in ("router.prefill_wait_s", "router.decode_wait_s"):
        assert name in summary["histograms"], name
    # the orchestration span is pinned and present
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    assert any(sp.get("name") == "router.migrate" for sp in spans)
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "migration:" in report and "queue split:" in report


def test_rolling_drain_with_parked_migrations(tiny_model):
    """Rolling drain of a prefill replica with migrations in flight:
    parked entries are swept (nothing pullable afterwards, nothing
    leaked) and capacity steps down one replica at a time."""
    cfg = _cfg()
    sup, router = _cluster(cfg)
    try:
        # Park two requests directly on the prefill replica (phase one
        # of the pipeline), then drain with the pulls never issued.
        import urllib.request
        port = sup.replicas()[0].port
        for i in range(2):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(
                    {"id": f"park-{i}", "prompt_tokens": _prompt(21, salt=i),
                     "max_new_tokens": 4, "prefill_only": True}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as resp:
                obj = json.loads(resp.read())
            assert obj["finish_reason"] == "prefilled", obj
        sched0 = _worker_sched(sup, 0)
        assert sched0.parked_count == 2
        progress = []
        sup.rolling_drain(timeout_s=20.0, progress=progress.append)
        assert progress == [1, 0]          # never zero before the end
        assert sched0.parked_count == 0    # swept at the drain cutoff
        sched0.engine.pool.leak_check()
        assert (sched0.engine.pool.blocks_used
                == sched0.engine.pool.trie_only_blocks)
    finally:
        router.stop()
        sup.shutdown()


# ------------------------------------------------------------ benchmark
def test_bench_disaggregate_with_prefill_kills(tmp_path):
    """benchmarks/serving.py --disaggregate --kill-rate aimed at the
    prefill tier: the record pins lost == 0 under kills, carries the
    migration GB/s block and the prefill/decode queueing split, and
    the run-dir artifacts are schema-valid."""
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import serving as bench

    faults.install(FaultPlan.parse("replica.kv_export:delay=0.02x*"))
    run_dir = str(tmp_path / "disbench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--disaggregate", "--prefill-replicas", "2",
         "--decode-replicas", "1", "--kill-rate", "8",
         "--requests", "12", "--concurrency", "4",
         "--prompt-len-mix", "6,21", "--max-new-tokens", "6",
         "--max-batch-size", "2", "--max-len", "64",
         "--max-prefill-len", "8", "--kv-block-size", "8",
         "--seed", "5", "--run-dir", run_dir]))
    assert rec["disaggregate"] is True
    assert rec["roles"] == ["prefill", "prefill", "decode"]
    assert rec["answered"] == 12 and rec["lost"] == 0
    assert rec["kills"] >= 1
    # kills were aimed at the prefill tier
    assert all(rid in (0, 1) for rid in rec["killed_rids"])
    mig = rec["migration"]
    assert mig["count"] >= 1 and mig["bytes"] > 0
    assert mig["gb_per_s"] >= 0
    assert rec["prefill_wait_s"]["p50"] >= 0
    assert rec["decode_wait_s"]["p50"] >= 0
    assert rec["tpot_s"]["p50"] > 0
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
