"""Test rig: force an 8-device virtual CPU platform BEFORE jax initializes,
so collectives/sharding tests run the real multi-chip code paths on any host
(SURVEY.md §4 test strategy)."""

import os

# Force CPU regardless of the ambient platform (the dev box exports
# JAX_PLATFORMS=axon for its single real TPU chip; tests need 8 virtual
# devices for the multi-chip paths). Plugins (jaxtyping) import jax before
# this conftest runs, so the env default is already baked — override via
# jax.config, which works any time before backend initialization.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
