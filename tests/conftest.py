"""Test rig: force an 8-device virtual CPU platform BEFORE jax initializes,
so collectives/sharding tests run the real multi-chip code paths on any host
(SURVEY.md §4 test strategy)."""

import os

# Force CPU regardless of the ambient platform (the dev box exports
# JAX_PLATFORMS=axon for its single real TPU chip; tests need 8 virtual
# devices for the multi-chip paths). Plugins (jaxtyping) import jax before
# this conftest runs, so the env default is already baked — override via
# jax.config, which works any time before backend initialization.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache: the suite is compile-bound on the
# 1-core build box (~40 CLI tests each jitting multi-second programs), and
# identical programs recur both across runs and across the worker processes
# the multi-process tests spawn (workers inherit the env var set here).
import sys as _sys

_sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".."))
from nezha_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_compile_cache,
)

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
enable_persistent_compile_cache()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


def worker_env():
    """Environment for worker OS processes (one-device hosts): repo root on
    PYTHONPATH (extended, never replaced), the suite's forced 8-device flag
    scrubbed so each worker sees its own single CPU device. Workers inherit
    JAX_COMPILATION_CACHE_DIR (set above), so repeated launches of the same
    tiny-preset programs deserialize instead of recompiling."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    return env


class TwoRankElastic:
    """Scaffolding for the elastic-recovery CLI tests: a 2-rank mlp_mnist
    control-plane world (`--on-failure rejoin`, shared --ckpt-dir,
    coordinator on rank 0), per-rank stderr files, metrics-line polling,
    and guaranteed process reaping. Tests drive kills/relaunches."""

    def __init__(self, tmp_path, rejoin_timeout="120"):
        import socket
        import sys

        self.tmp_path = tmp_path
        self.env = worker_env()
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        self.ck = str(tmp_path / "ck")
        self.base = [sys.executable, "-m", "nezha_tpu.cli.train",
                     "--config", "mlp_mnist", "--batch-size", "64",
                     "--platform", "cpu", "--log-every", "25",
                     "--failure-check-every", "5", "--ckpt-dir", self.ck,
                     "--coordinator", f"127.0.0.1:{self.port}",
                     "--no-jax-distributed", "--on-failure", "rejoin",
                     "--rejoin-timeout", str(rejoin_timeout)]
        self.procs = []
        self.errfiles = []

    def launch(self, tag, extra):
        import subprocess

        errf = open(self.tmp_path / f"{tag}.err", "w+")
        self.errfiles.append(errf)
        p = subprocess.Popen(self.base + extra, stdout=subprocess.DEVNULL,
                             stderr=errf, text=True, env=self.env)
        self.procs.append(p)
        return p

    def err(self, tag) -> str:
        return (self.tmp_path / f"{tag}.err").read_text()

    def wait_for(self, tag, needle, proc, timeout=120):
        """Poll a rank's stderr for ``needle`` while it stays alive."""
        import time

        deadline = time.monotonic() + timeout
        while needle not in self.err(tag):
            assert proc.poll() is None, self.err(tag)
            assert time.monotonic() < deadline, self.err(tag)
            time.sleep(0.25)

    def cleanup(self):
        for p in self.procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for f in self.errfiles:
            f.close()


def run_worker_processes(argv_per_rank, timeout=300):
    """Launch one OS process per argv list (modelling one-device hosts) and
    return [(returncode, stdout, stderr)]. Shared harness for the
    multi-process launch tests; workers always reaped on timeout."""
    import subprocess

    env = worker_env()
    procs = [subprocess.Popen(argv, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=env)
             for argv in argv_per_rank]
    try:
        outs = [p.communicate(timeout=timeout) for p in procs]
    finally:  # never leak a wedged worker (hung initialize, etc.)
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return [(p.returncode, out, err) for p, (out, err) in zip(procs, outs)]
