"""Aux-subsystem tests: metrics JSONL, step timing, profiling wrappers,
rank-tagged logging, and the Trainer's tracer/failure-detection hooks."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import utils
from nezha_tpu.utils.metrics import read_metrics


def test_metrics_logger_roundtrip(tmp_path):
    path = tmp_path / "m.jsonl"
    with utils.MetricsLogger(str(path)) as log:
        log(1, {"loss": jnp.float32(2.5), "lr": 1e-3})
        log(2, {"loss": 2.0})
    recs = read_metrics(str(path))
    assert [r["step"] for r in recs] == [1, 2]
    assert recs[0]["loss"] == 2.5
    assert recs[0]["lr"] == 1e-3
    assert all("ts" in r for r in recs)


def test_metrics_logger_appends(tmp_path):
    path = tmp_path / "m.jsonl"
    with utils.MetricsLogger(str(path)) as log:
        log(1, {"a": 1})
    with utils.MetricsLogger(str(path)) as log:
        log(2, {"a": 2})
    assert len(read_metrics(str(path))) == 2


def test_step_timer_windows():
    timer = utils.StepTimer(window=3)
    x = jnp.float32(0.0)
    assert timer.tick(x) is None  # opens window
    assert timer.tick(x) is None
    assert timer.tick(x) is None
    rate = timer.tick(x)  # 3rd counted step closes window
    assert rate is not None and rate > 0


def test_annotate_and_profile_trace(tmp_path):
    # Smoke: annotation composes with jit; trace produces files.
    @jax.jit
    def f(x):
        with utils.annotate("double"):
            return x * 2

    with utils.profile_trace(str(tmp_path / "trace")):
        f(jnp.ones((8, 8))).block_until_ready()
    produced = []
    for root, _, files in os.walk(tmp_path / "trace"):
        produced += files
    assert produced, "profiler wrote no trace files"


def test_tracer_start_stop(tmp_path):
    tracer = utils.Tracer(str(tmp_path / "t"), start_step=2, num_steps=2)
    for step in range(1, 6):
        tracer.maybe_trace(step)
        jnp.ones(4).block_until_ready()
    assert not tracer._active
    produced = []
    for root, _, files in os.walk(tmp_path / "t"):
        produced += files
    assert produced


def test_tracer_disabled_is_noop():
    tracer = utils.Tracer(None)
    for step in range(5):
        tracer.maybe_trace(step)  # must not raise or start anything
    assert not tracer.enabled


def test_rank_tagged_logging():
    # Attach our own stream: the default handler binds sys.stderr at first
    # configuration, which under pytest may be another test's capture.
    import io
    import logging as py_logging

    from nezha_tpu.utils.logging import _RankFilter

    utils.set_rank(3)
    logger = utils.get_logger("nezha_tpu.test")
    stream = io.StringIO()
    handler = py_logging.StreamHandler(stream)
    handler.setFormatter(py_logging.Formatter("[rank %(rank)s] %(message)s"))
    handler.addFilter(_RankFilter())
    logger.addHandler(handler)
    try:
        logger.info("hello from a pod")
    finally:
        logger.removeHandler(handler)
        utils.set_rank(0)
    assert "[rank 3] hello from a pod" in stream.getvalue()


def test_trainer_failure_detection(tmp_path):
    """A Trainer polling a ProcessGroup must checkpoint and raise when a
    peer rank dies mid-training."""
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")
    from nezha_tpu import dist, ops, optim
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.checkpoint import latest_step
    from nezha_tpu.train.loop import Trainer

    def loss_fn(logits, batch):
        return ops.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"])

    def batches():
        rng = np.random.RandomState(0)
        while True:
            yield {"image": rng.rand(8, 784).astype(np.float32),
                   "label": rng.randint(0, 10, 8).astype(np.int32)}

    with dist.Coordinator(world_size=2, heartbeat_timeout_s=0.5) as coord:
        g0 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.1)
        g1 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.1)
        trainer = Trainer(
            MLP(hidden=(32,)), optim.sgd(1e-2), loss_fn,
            checkpoint_dir=str(tmp_path / "ckpt"),
            process_group=g0, failure_check_every=1, log_every=0)
        trainer.initialize()
        # Train a few healthy steps, then kill the peer.
        trainer.fit(batches(), steps=3)
        g1.close()
        time.sleep(1.0)  # past heartbeat timeout
        with pytest.raises(RuntimeError, match=r"rank\(s\) \[1\] failed"):
            trainer.fit(batches(), steps=50)
        # Progress was preserved before raising.
        assert latest_step(str(tmp_path / "ckpt")) == trainer.global_step
        g0.leave()


def test_trainer_resume_from_checkpoint(tmp_path):
    """Checkpoint/resume: a new Trainer picks up step count and state."""
    from nezha_tpu import ops, optim
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.loop import Trainer

    def loss_fn(logits, batch):
        return ops.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"])

    def batches():
        rng = np.random.RandomState(0)
        while True:
            yield {"image": rng.rand(8, 784).astype(np.float32),
                   "label": rng.randint(0, 10, 8).astype(np.int32)}

    def make(mldir):
        return Trainer(MLP(hidden=(32,)), optim.sgd(1e-2), loss_fn,
                       checkpoint_dir=str(mldir), checkpoint_every=5,
                       log_every=0)

    t1 = make(tmp_path)
    t1.fit(batches(), steps=10)
    w1 = t1.state["variables"]["params"]["head"]["w"]

    t2 = make(tmp_path)
    t2.initialize(resume=True)
    assert t2.global_step == 10
    np.testing.assert_allclose(
        t2.state["variables"]["params"]["head"]["w"], w1, atol=0)


def test_tracer_rebases_window_on_resumed_steps(tmp_path):
    """Resume at step 5000 with start_step=10: a full window must still be
    captured, exactly once."""
    import jax.numpy as jnp

    tracer = utils.Tracer(str(tmp_path / "rt"), start_step=10, num_steps=2)
    for step in range(5000, 5008):
        tracer.maybe_trace(step)
        jnp.ones(2).block_until_ready()
    assert not tracer._active and tracer._done
    produced = []
    for root, _, files in os.walk(tmp_path / "rt"):
        produced += files
    assert produced


def test_memory_metrics_names_and_cpu_noop(monkeypatch):
    """memory_metrics maps backend stats to stable metric names, and is an
    empty dict where the backend exposes none (CPU)."""
    import jax

    from nezha_tpu.tensor import memory_metrics
    assert memory_metrics() == {}  # CPU backend: no stats, no crash

    class FakeDev:
        def memory_stats(self):
            return {"bytes_in_use": 123, "peak_bytes_in_use": 456,
                    "largest_free_block_bytes": 9}

    out = memory_metrics(FakeDev())
    assert out == {"hbm_bytes_in_use": 123, "hbm_peak_bytes": 456}


def test_cli_log_memory_flag_is_safe_off_tpu(tmp_path):
    import json as _json

    from nezha_tpu.cli.train import build_parser, run
    mf = tmp_path / "m.jsonl"
    run(build_parser().parse_args(
        ["--config", "mlp_mnist", "--steps", "4", "--batch-size", "16",
         "--log-every", "2", "--log-memory", "--metrics-file", str(mf)]))
    recs = [_json.loads(l) for l in mf.read_text().strip().splitlines()]
    assert recs and all("loss" in r for r in recs)  # flag adds nothing on CPU


def test_cli_profile_steps_window(tmp_path):
    """--profile-steps START:COUNT captures a bounded trace window into
    --profile-dir (and validates its inputs)."""
    import pytest

    from nezha_tpu.cli.train import build_parser, run
    pd = tmp_path / "prof"
    run(build_parser().parse_args(
        ["--config", "mlp_mnist", "--steps", "8", "--batch-size", "16",
         "--profile-dir", str(pd), "--profile-steps", "3:2",
         "--log-every", "4"]))
    # jax writes trace artifacts under plugins/profile/<ts>/.
    assert any(pd.rglob("*.pb")) or any(pd.rglob("*.json.gz")), \
        list(pd.rglob("*"))
    with pytest.raises(SystemExit, match="START:COUNT"):
        run(build_parser().parse_args(
            ["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
             "--profile-dir", str(pd), "--profile-steps", "banana"]))
    with pytest.raises(SystemExit, match="COUNT >= 1"):
        run(build_parser().parse_args(
            ["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
             "--profile-dir", str(pd), "--profile-steps", "10:0"]))
    with pytest.raises(SystemExit, match="START >= 1"):
        run(build_parser().parse_args(
            ["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
             "--profile-dir", str(pd), "--profile-steps", "0:3"]))
    with pytest.raises(SystemExit, match="needs --profile-dir"):
        run(build_parser().parse_args(
            ["--config", "mlp_mnist", "--steps", "1", "--batch-size", "8",
             "--profile-steps", "1:1"]))
