"""Int8-quantized KV blocks (ISSUE 9): the shared ops/quant core, the
per-block-scaled int8 paged pool, in-kernel dequant, and the serving
invariants re-proven under ``kv_dtype="int8"``.

Covers the extracted quantization core (round-trip error bounds, the
all-zero scale guard, deterministic NaN/inf saturation, and a
bit-identity regression pin that the wire collectives survived the
extraction), greedy decode parity (int8 engine vs the f32 engine and
one-shot generate; flash-decode kernel vs the gathered XLA fallback;
h=1 vs h=8 bit-identity), copy-on-write carrying scales with blocks
(live donor re-hits an intact cache), the stale-KV reuse invariant with
POISONED int8 storage AND poisoned scale rows, eviction freeing scales
with their blocks, the serve.kv.quant_error / bytes_resident /
quant_bits telemetry pins, the worker-argv CLI passthrough, the bench
record's dtype/bytes fields, and a seeded chaos acceptance at horizon 4
asserting zero slot/block/scale leaks with the frozen program set.
"""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.models.generate import generate
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.ops import quant
from nezha_tpu.serve import (
    Engine,
    Request,
    Scheduler,
    ServeConfig,
)

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
# Matches test_paged_kv.PCFG, with int8 KV blocks: block_size 4 so tiny
# prompts span real blocks (full-block prefix hits, COW, lazy growth,
# per-block requant all fire at test sizes).
QCFG = ServeConfig(max_batch_size=3, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32, kv_block_size=4,
                   kv_dtype="int8")
FCFG = dataclasses.replace(QCFG, kv_dtype="bf16")   # f32 blocks (cache_dtype)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for sub in ("tools", "benchmarks"):
    p = os.path.join(_ROOT, sub)
    if p not in sys.path:
        sys.path.insert(0, p)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


def _drain(sched, max_iters=400):
    sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"


def _greedy_ref(model, variables, prompt, n):
    return np.asarray(generate(
        model, variables, np.asarray([prompt], np.int32),
        max_new_tokens=n, temperature=0.0,
        cache_dtype=jnp.float32))[0, len(prompt):].tolist()


def _run(model, variables, cfg, reqs):
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    rids = [sched.submit(Request(**kw)) for kw in reqs]
    _drain(sched)
    return eng, sched, [sched.results[r].tokens for r in rids]


# ------------------------------------------------------ ops/quant core
def test_quant_roundtrip_error_bound():
    """Symmetric absmax int8: per-block round-trip error is bounded by
    half a quantization step (scale / 2 = amax / 254), no clipping
    error at the extremes (amax itself maps to exactly ±127)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(6, 4, 8, 16)) * 3.0, jnp.float32)
    q, s = quant.quantize_kv_block(x)
    assert q.dtype == jnp.int8 and s.shape == (6, 4)
    deq = quant.dequantize_kv_block(q, s, jnp.float32)
    err = np.abs(np.asarray(x) - np.asarray(deq))
    bound = np.asarray(s)[..., None, None] * 0.5 * (1 + 1e-6)
    assert (err <= bound).all()
    # The histogram sample helper agrees with the direct computation.
    assert float(quant.kv_roundtrip_error(x)) == pytest.approx(
        float(err.max()), rel=1e-6)
    # amax elements survive exactly (no clip loss at the extremes).
    amax_pos = np.unravel_index(np.argmax(np.abs(np.asarray(x))),
                               x.shape)
    assert np.asarray(q)[amax_pos] in (-127, 127)


def test_quant_all_zero_block_scale_guard():
    """An all-zero block takes scale 1.0 (the shared guard): quantizes
    to exact zeros, dequantizes to exact zeros, no div-by-zero, no
    NaN — the state every freshly-allocated pool block starts in."""
    z = jnp.zeros((3, 2, 4, 8), jnp.float32)
    q, s = quant.quantize_kv_block(z)
    assert (np.asarray(s) == 1.0).all()
    assert (np.asarray(q) == 0).all()
    assert (np.asarray(quant.dequantize_kv_block(q, s)) == 0.0).all()
    assert float(quant.kv_roundtrip_error(z)) == 0.0
    # Wire layout shares the guard.
    qw, sw = quant.quantize_blocks(jnp.zeros((256,), jnp.float32), 64)
    assert (np.asarray(sw) == 1.0).all() and (np.asarray(qw) == 0).all()


def test_quant_nonfinite_inputs_saturate_deterministically():
    """NaN/±inf inputs (the PR-4 fault surface reaching a KV write)
    saturate deterministically — NaN -> 0, ±inf -> ±f32 max — and the
    outputs (including scales) are always finite; two calls agree
    bit-for-bit. A NaN must never become a NaN SCALE poisoning every
    other element of the block."""
    bad = jnp.asarray([[[np.nan, np.inf, -np.inf, 1.0],
                        [0.5, np.nan, -2.0, np.inf]]], jnp.float32)
    q1, s1 = quant.quantize_kv_block(bad)
    q2, s2 = quant.quantize_kv_block(bad)
    assert np.array_equal(np.asarray(q1), np.asarray(q2))
    assert np.array_equal(np.asarray(s1), np.asarray(s2))
    assert np.isfinite(np.asarray(s1)).all()
    san = np.asarray(quant.sanitize(bad))
    assert san[0, 0, 0] == 0.0                          # NaN -> 0
    assert san[0, 0, 1] == np.float32(quant.SATURATE_MAX)   # +inf
    assert san[0, 0, 2] == -np.float32(quant.SATURATE_MAX)  # -inf
    # The whole round trip stays finite (SATURATE_MAX sits far enough
    # below f32 max that 127 * (amax/127) cannot overflow).
    assert np.isfinite(np.asarray(quant.dequantize_kv_block(q1, s1))).all()
    assert np.isfinite(float(quant.kv_roundtrip_error(bad)))


def test_wire_collectives_bit_identical_after_extraction():
    """The regression pin ISSUE 9 demands: parallel/quantized.py's
    quantize/dequantize (now imported from ops/quant.py) must be
    BIT-IDENTICAL to the pre-extraction in-module implementation —
    re-derived here as golden code copied from the PR-1 source."""
    from nezha_tpu.parallel import quantized as wire

    def golden_quantize_blocks(x, block):
        xb = x.reshape(*x.shape[:-1], x.shape[-1] // block, block)
        amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
        scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(
            jnp.float32)
        q = jnp.clip(jnp.round(xb / scale), -127.0, 127.0).astype(
            jnp.int8)
        return q, scale

    rng = np.random.default_rng(7)
    for shape, block in (((2048,), 512), ((4, 768), 256), ((640,), 64)):
        x = jnp.asarray(rng.normal(size=shape) * 10, jnp.float32)
        q_new, s_new = wire._quantize_blocks(x, block)
        q_old, s_old = golden_quantize_blocks(x, block)
        assert np.array_equal(np.asarray(q_new), np.asarray(q_old))
        assert np.array_equal(np.asarray(s_new), np.asarray(s_old))
        assert np.array_equal(
            np.asarray(wire._dequantize(q_new, s_new)),
            np.asarray(q_old.astype(jnp.float32) * s_old))
        # And the public round-trip (the single-hop wire error probe).
        rt = wire.quantize_roundtrip(x, block)
        q, s = golden_quantize_blocks(
            jnp.pad(x.reshape(-1), (0, (-x.size) % block)), block)
        golden_rt = (q.astype(jnp.float32) * s).reshape(-1)[
            :x.size].reshape(x.shape)
        assert np.array_equal(np.asarray(rt), np.asarray(golden_rt))


# --------------------------------------------------------- pool layer
def test_quant_pool_scales_move_with_blocks(model_and_vars):
    """The single invariant: a block and its scale row move, ref-count,
    evict, and free together — scales are block-indexed leaves of the
    same caches pytree, so COW copies them and leak_check's structure
    oracle catches a caches tree rebuilt without them."""
    from nezha_tpu.serve import PagedSlotPool
    model, _ = model_and_vars
    pool = PagedSlotPool(model, capacity=2, max_len=16,
                         dtype=jnp.float32, block_size=4,
                         quantized=True)
    assert pool.quantized
    for layer in pool.caches:
        assert layer["k"].dtype == jnp.int8
        assert layer["k_scale"].shape == (pool.num_blocks,
                                          model.cfg.num_heads)
    # int8 block footprint ~ half of f32's quarter... compare against
    # the unquantized pool: f32 block = 4 bytes/elt, int8 = 1 + scales.
    dense = PagedSlotPool(model, capacity=2, max_len=16,
                          dtype=jnp.float32, block_size=4)
    assert pool.bytes_per_block < dense.bytes_per_block / 3
    s = pool.alloc()
    pool.bind_for_prompt(s, [1, 2, 3, 4, 5])
    pool.prepare_write(s, 0, 8)
    # Stamp block b0's scale row, COW-copy it, check the copy carried.
    b0 = int(pool.tables_host[s, 0])
    pool.caches = [dict(layer, k_scale=layer["k_scale"].at[b0].set(7.5))
                   for layer in pool.caches]
    pool._refs[b0] += 1                     # simulate a second holder
    pool.prepare_write(s, 0, 4)             # -> COW of b0
    nb = int(pool.tables_host[s, 0])
    assert nb != b0
    assert float(pool.caches[0]["k_scale"][nb, 0]) == 7.5
    pool._refs[b0] -= 1
    pool._free_blocks.append(b0) if pool._refs[b0] == 0 else None
    pool.leak_check()
    # Structure oracle: dropping a scale leaf is caught.
    broken = [{k: v for k, v in layer.items() if k != "v_scale"}
              for layer in pool.caches]
    good = pool.caches
    pool.caches = broken
    with pytest.raises(AssertionError, match="v_scale"):
        pool.leak_check()
    pool.caches = good
    pool.free(s)
    pool.leak_check()


# ------------------------------------------------------ engine parity
def test_int8_engine_greedy_parity_and_frozen_programs(model_and_vars):
    """Greedy, sampled, and chunked requests decode token-identically
    on the int8 and f32 engines (the tiny model's logit gaps dominate
    the bounded quant error — deterministic, pinned), greedy matches
    one-shot generate(), and the frozen program contract holds."""
    model, variables = model_and_vars
    reqs = [dict(prompt=[5, 17, 3, 42], max_new_tokens=10),
            dict(prompt=[7, 7], max_new_tokens=9, temperature=0.9,
                 top_k=10, seed=7),
            dict(prompt=[(7 * i + 3) % 97 for i in range(20)],
                 max_new_tokens=6)]
    eng_f, _, out_f = _run(model, variables, FCFG, reqs)
    eng_q, _, out_q = _run(model, variables, QCFG, reqs)
    assert out_q == out_f
    assert out_q[0] == _greedy_ref(model, variables,
                                   reqs[0]["prompt"], 10)
    assert out_q[2] == _greedy_ref(model, variables,
                                   reqs[2]["prompt"], 6)
    stats = eng_q.compile_stats()
    assert stats["entries"] == stats["misses"] == \
        1 + len(QCFG.prefill_buckets)
    eng_q.pool.leak_check()
    # bytes_resident reflects the narrow storage: at identical block
    # counts the int8 pool's resident bytes are < 1/3 of the f32
    # pool's (int8+scales vs 4-byte elements).
    assert eng_q.pool.bytes_per_block < eng_f.pool.bytes_per_block / 3


def test_int8_kernel_vs_xla_fallback_parity(model_and_vars):
    """decode_impl='kernel' (in-loop dequant) and 'xla' (gathered
    dequant) produce identical tokens: both apply the SAME dequant
    expression, so the escape hatch stays valid for the int8 cache."""
    model, variables = model_and_vars
    reqs = [dict(prompt=[5, 17, 3, 42], max_new_tokens=10),
            dict(prompt=[7, 7], max_new_tokens=9, temperature=0.9,
                 top_k=10, seed=7),
            dict(prompt=[(7 * i + 3) % 97 for i in range(20)],
                 max_new_tokens=6)]
    _, _, out_k = _run(model, variables,
                       dataclasses.replace(QCFG, decode_impl="kernel"),
                       reqs)
    _, _, out_x = _run(model, variables,
                       dataclasses.replace(QCFG, decode_impl="xla"),
                       reqs)
    assert out_k == out_x


def test_int8_horizon_bit_identity(model_and_vars):
    """h=1 vs h=8 bit-identity survives quantization: the per-step
    block requant depends only on (pool state, new row), which is the
    same sequence of writes whatever the horizon."""
    model, variables = model_and_vars
    reqs = [dict(prompt=[5, 17, 3, 42], max_new_tokens=10),
            dict(prompt=[9, 1], max_new_tokens=12, temperature=0.8,
                 top_k=12, seed=3)]
    _, _, o1 = _run(model, variables,
                    dataclasses.replace(QCFG, decode_horizon=1), reqs)
    _, _, o8 = _run(model, variables,
                    dataclasses.replace(QCFG, decode_horizon=8), reqs)
    assert o1 == o8


def test_int8_cow_preserves_donor_cache(model_and_vars):
    """COW carries scales: an exactly-block-aligned full-prefix hit
    writes into its last shared block (COWed first); the donor's
    cached block AND scale row stay intact — a third identical request
    re-hits the cache and still decodes identically."""
    model, variables = model_and_vars
    prompt = [(5 * i + 11) % 97 for i in range(12)]   # exactly 3 blocks
    eng = Engine(model, variables, QCFG)
    sched = Scheduler(eng)
    ref = _greedy_ref(model, variables, prompt, 6)
    a = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    _drain(sched)
    assert sched.results[a].tokens == ref
    b = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    c = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    _drain(sched)
    assert eng.pool.prefix_hits == 2 and eng.pool.cow_copies >= 2
    assert sched.results[b].tokens == ref
    assert sched.results[c].tokens == ref
    eng.pool.leak_check()


def test_int8_stale_kv_and_stale_scales_never_attendable(
        model_and_vars):
    """The stale-KV reuse invariant extended to scales: retire a
    request, poison every FREED block's int8 content with ±127 and its
    scale rows with a huge sentinel (1e3), then serve a new request
    through the same storage — its tokens must match a clean-engine
    reference exactly. This covers both failure modes quantization
    adds: attending a stale position (huge dequantized value skews
    logits) and folding stale content into a fresh block's absmax (a
    1e3-scaled garbage entry entering the requant window would crush
    the real entries' precision)."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(QCFG, prefix_cache=False)
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    first = sched.submit(Request(
        prompt=[(7 * i + 1) % 97 for i in range(20)], max_new_tokens=8))
    _drain(sched)
    assert sched.results[first].finish_reason == "length"
    idx = jnp.asarray(sorted(eng.pool._free_blocks), jnp.int32)
    eng.pool.caches = [
        {"k": layer["k"].at[idx].set(127),
         "v": layer["v"].at[idx].set(-127),
         "k_scale": layer["k_scale"].at[idx].set(1.0e3),
         "v_scale": layer["v_scale"].at[idx].set(1.0e3)}
        for layer in eng.pool.caches]
    prompt2 = [9, 8, 7, 6, 5]
    second = sched.submit(Request(prompt=prompt2, max_new_tokens=8))
    _drain(sched)
    res = sched.results[second]
    assert res.finish_reason == "length", res.error
    assert res.tokens == _greedy_ref(model, variables, prompt2, 8)
    eng.pool.leak_check()


def test_int8_eviction_frees_scales_with_blocks(model_and_vars):
    """Eviction under pressure works on the quantized pool, and
    clearing the prefix cache leaves ZERO blocks resident — the
    eviction-frees-scales oracle (scales share the block index, so a
    freed block's scale row is recycled with it; leak_check's
    structure oracle confirms no path dropped the buffers)."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(QCFG, max_batch_size=1, kv_num_blocks=8)
    eng = Engine(model, variables, cfg)
    sched = Scheduler(eng)
    p1 = [(3 * i + 2) % 97 for i in range(12)]       # 3 full blocks
    sched.submit(Request(prompt=p1, max_new_tokens=4))
    _drain(sched)
    assert len(eng.pool.trie) == 3
    p2 = [(5 * i + 1) % 97 for i in range(20)]
    r = sched.submit(Request(prompt=p2, max_new_tokens=3))
    _drain(sched)
    assert sched.results[r].finish_reason == "length"
    assert len(eng.pool.trie) < 3 + 5    # eviction happened
    eng.pool.leak_check()
    eng.pool.clear_prefix_cache()
    eng.pool.leak_check()
    assert eng.pool.blocks_used == 0
    assert eng.pool.bytes_resident == 0


# ------------------------------------------------- telemetry + chaos
def test_int8_chaos_zero_leaks_frozen_programs_schema(model_and_vars,
                                                      tmp_path):
    """The PR-7 chaos acceptance re-run on the int8 pool at horizon 4:
    seeded prefill errors + NaN bursts + kv.bind failures over 16
    templated requests (prefix hits + COW + per-block requant in
    play). Every request gets exactly one result, zero slot AND block
    leaks (scale oracle included), frozen program set, and the run-dir
    artifacts pass the pinned schema including serve.kv.quant_error /
    bytes_resident / quant_bits; the report labels the dtype."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "chaos_int8")
    obs.start_run(run_dir, meta={"kind": "chaos_int8"})
    try:
        cfg = dataclasses.replace(QCFG, decode_horizon=4,
                                  queue_capacity=16)
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        faults.install(faults.FaultPlan.parse(
            "serve.prefill:error%0.08;serve.step.logits:nan%0.05;"
            "serve.kv.bind:error%0.03", seed=7))
        try:
            prefix = [(3 * i + 5) % 97 for i in range(8)]
            rids = []
            for i in range(16):
                prompt = (prefix + [i % 97, (2 * i) % 97]
                          if i % 2 else
                          [(11 * i + j) % 97 for j in range(6)])
                rids.append(sched.submit(Request(
                    prompt=prompt, max_new_tokens=6,
                    temperature=0.8 if i % 3 == 0 else 0.0,
                    top_k=10 if i % 3 == 0 else None, seed=i,
                    request_id=f"c{i}")))
            _drain(sched)
        finally:
            faults.clear()
        assert set(rids) <= set(sched.results)
        reasons = {sched.results[r].finish_reason for r in rids}
        assert reasons <= {"length", "error"}
        assert eng.pool.num_free == cfg.max_batch_size
        eng.pool.leak_check()
        stats = eng.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(cfg.prefill_buckets)
        eng.pool.clear_prefix_cache()
        eng.pool.leak_check()
        assert eng.pool.blocks_used == 0
        # Quant error was sampled at prefill writes and is bounded
        # (the tiny model's activations are O(10); a huge p-max would
        # mean a stale block's garbage entered a requant window).
        h = obs.histogram("serve.kv.quant_error").summary()
        assert h["count"] > 0
        assert 0 <= h["max"] < 10.0
    finally:
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert "serve.kv.quant_error" in summary["histograms"]
    assert "serve.kv.bytes_resident" in summary["gauges"]
    assert summary["gauges"]["serve.kv.quant_bits"] == 8
    # Dropping a quant instrument must FAIL the pinned schema.
    del summary["histograms"]["serve.kv.quant_error"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.kv.quant_error" in e for e in check_run_dir(run_dir))
    summary["histograms"]["serve.kv.quant_error"] = dict(
        count=1, sum=0.01, min=0.01, max=0.01, mean=0.01, p50=0.01,
        p90=0.01, p99=0.01)
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "dtype int8" in report and "quant err p99" in report


def test_bf16_run_reports_quant_schema_with_zeros(model_and_vars,
                                                 tmp_path):
    """Layout/dtype-invariant schema: a DEFAULT (bf16) serving run
    still carries the quant instruments — quant_bits reports the
    storage width, quant_error stays empty, and the report renders the
    dtype label without a quant-error clause."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "bf16_run")
    obs.start_run(run_dir, meta={"kind": "serve"})
    try:
        eng = Engine(model, variables, FCFG)
        sched = Scheduler(eng)
        sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=2))
        _drain(sched)
        assert obs.histogram("serve.kv.quant_error").summary()[
            "count"] == 0
    finally:
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert summary["gauges"]["serve.kv.quant_bits"] == 32  # f32 pool
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "dtype f32" in report and "quant err" not in report


# ------------------------------------------------------- CLI + bench
def test_serve_cli_kv_dtype_passthrough():
    """--kv-dtype reaches ServeConfig and the spawned worker argv."""
    from nezha_tpu.cli.serve import _worker_argv, build_parser
    args = build_parser().parse_args(
        ["--random-init", "--kv-dtype", "int8", "--http", "8000",
         "--replicas", "2"])
    assert args.kv_dtype == "int8"
    argv = _worker_argv(args, 0, 9000)
    i = argv.index("--kv-dtype")
    assert argv[i + 1] == "int8"
    # Default stays bf16 (the bit-identical path).
    args2 = build_parser().parse_args(["--random-init"])
    assert args2.kv_dtype == "bf16"


def test_serving_benchmark_kv_dtype_record(tmp_path):
    """benchmarks/serving.py --kv-dtype int8: the record carries the
    dtype and byte accounting (bytes_per_block, peak_bytes_resident),
    requests finish cleanly, and the artifacts pass the pinned
    schema."""
    import serving as bench

    run_dir = str(tmp_path / "int8_bench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--requests", "6", "--concurrency", "3", "--max-new-tokens",
         "4", "--max-batch-size", "3", "--max-len", "48",
         "--max-prefill-len", "8", "--kv-block-size", "4",
         "--kv-dtype", "int8", "--run-dir", run_dir]))
    assert rec["finished"] == 6
    assert rec["kv"]["dtype"] == "int8"
    assert rec["kv"]["bytes_per_block"] > 0
    assert rec["kv"]["peak_bytes_resident"] >= \
        rec["kv"]["peak_blocks_used"] * rec["kv"]["bytes_per_block"] > 0
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []

    rec_b = bench.run(bench.build_parser().parse_args(
        ["--requests", "4", "--concurrency", "2", "--max-new-tokens",
         "2", "--max-batch-size", "2", "--max-len", "32",
         "--max-prefill-len", "8", "--kv-block-size", "4"]))
    assert rec_b["kv"]["dtype"] == "bf16"
    # Same block geometry: int8 blocks cost a fraction of bf16's.
    assert rec["kv"]["bytes_per_block"] < rec_b["kv"]["bytes_per_block"]


def test_serveconfig_kv_dtype_validation():
    with pytest.raises(ValueError, match="kv_dtype"):
        ServeConfig(kv_dtype="fp4")
    with pytest.raises(ValueError, match="paged"):
        ServeConfig(kv_layout="dense", kv_dtype="int8")
