"""Property-based IR tests (hypothesis): randomly-structured graphs must
interpret and XLA-compile to the same values, autograd must accept any
scalar-output graph, and the Executor's structural fingerprint must be
stable (same structure) and collision-free (different structure).

Derandomized: CI must not see fresh examples per run."""

import jax
import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from nezha_tpu.graph import Graph, compile_graph, grad_callable, to_callable
from nezha_tpu.runtime.executor import _graph_fingerprint

SHAPE = (4, 4)
_BIN = ("add", "sub", "mul", "matmul")
_UN = ("relu", "tanh", "sigmoid", "neg", "softmax")


@st.composite
def graphs(draw):
    """A random SSA DAG over [4,4] tensors ending in a scalar mean."""
    g = Graph("prop")
    n_inputs = draw(st.integers(1, 3))
    syms = [g.placeholder(SHAPE, name=f"x{i}") for i in range(n_inputs)]
    for _ in range(draw(st.integers(2, 8))):
        if draw(st.booleans()):
            op = draw(st.sampled_from(_BIN))
            a = syms[draw(st.integers(0, len(syms) - 1))]
            b = syms[draw(st.integers(0, len(syms) - 1))]
            syms.append(g._add(op, [a, b]))
        else:
            op = draw(st.sampled_from(_UN))
            a = syms[draw(st.integers(0, len(syms) - 1))]
            syms.append(g._add(op, [a]) if op != "softmax"
                        else g.softmax(a, axis=-1))
    g.output(g.mean(syms[-1]))
    return g, n_inputs


def _feeds(n, seed=0):
    r = np.random.RandomState(seed)
    # Small magnitudes: keeps exp/matmul chains finite through ~10 nodes.
    return [r.uniform(-1, 1, SHAPE).astype(np.float32) for _ in range(n)]


@settings(max_examples=25, deadline=None, derandomize=True)
@given(graphs())
def test_interpret_matches_compiled(gn):
    g, n = gn
    args = _feeds(n)
    eager = np.asarray(to_callable(g)(*args))
    compiled = np.asarray(compile_graph(g)(*args))
    np.testing.assert_allclose(eager, compiled, rtol=1e-5, atol=1e-6)
    assert np.isfinite(eager)


@settings(max_examples=10, deadline=None, derandomize=True)
@given(graphs())
def test_autograd_accepts_any_scalar_graph(gn):
    g, n = gn
    grads = grad_callable(g, wrt=tuple(range(n)))(*_feeds(n))
    grads = grads if isinstance(grads, tuple) else (grads,)
    for gr in grads:
        assert np.all(np.isfinite(np.asarray(gr)))


def _rebuild(g):
    """A FRESH Graph with the same structure (new Node objects), so the
    stability property tests structural identity, not object identity."""
    from nezha_tpu.graph.graph import Node

    g2 = Graph(g.name)
    g2.nodes = [Node(n.id, n.op, tuple(n.inputs), dict(n.attrs), n.name)
                for n in g.nodes]
    g2.placeholders = list(g.placeholders)
    g2.outputs = list(g.outputs)
    return g2


@settings(max_examples=25, deadline=None, derandomize=True)
@given(graphs())
def test_fingerprint_stable_and_structure_sensitive(gn):
    g, n = gn
    # Stable: a separately-built identical structure gives the identical
    # key (object identity must not leak into the fingerprint — the
    # Executor's compile cache dedupes on this).
    assert _graph_fingerprint(g) == _graph_fingerprint(_rebuild(g))
    # Sensitive: appending one more op must change it.
    g2 = _rebuild(g)
    g2._add("neg", [g.nodes[-1].id])
    assert _graph_fingerprint(g) != _graph_fingerprint(g2)
