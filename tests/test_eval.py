"""Evaluation-loop tests: metric accumulation, accuracy/perplexity
derivation, and the CLI --eval path."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import optim, ops
from nezha_tpu.models.mlp import MLP
from nezha_tpu.train.eval import accuracy, evaluate, lm_token_stats


def test_accuracy_exact_on_known_predictions():
    class Fixed:
        def apply(self, variables, batch, training=False):
            # Predict class = label for even rows, wrong for odd rows.
            b = batch["label"].shape[0]
            logits = jax.nn.one_hot(
                jnp.where(jnp.arange(b) % 2 == 0, batch["label"],
                          (batch["label"] + 1) % 10), 10) * 10.0
            return logits, {}

    batches = [{"image": np.zeros((8, 4), np.float32),
                "label": np.arange(8).astype(np.int32) % 10}
               for _ in range(3)]
    out = evaluate(Fixed(), {}, iter(batches), stat_fn=accuracy)
    assert out["count"] == 24
    assert out["accuracy"] == 0.5
    assert out["batches"] == 3


def test_perplexity_uniform_logits():
    """Uniform logits over V classes -> perplexity == V exactly."""
    V = 11

    class Uniform:
        def apply(self, variables, batch, training=False):
            b, s1 = batch["tokens"].shape
            return jnp.zeros((b, s1 - 1, V), jnp.float32), {}

    batches = [{"tokens": np.random.RandomState(i).randint(
        0, V, (2, 9)).astype(np.int32)} for i in range(2)]
    out = evaluate(Uniform(), {}, iter(batches), stat_fn=lm_token_stats)
    np.testing.assert_allclose(out["perplexity"], V, rtol=1e-5)


def test_evaluate_max_batches():
    class Zero:
        def apply(self, variables, batch, training=False):
            return jnp.zeros((batch["label"].shape[0], 10)), {}

    def forever():
        while True:
            yield {"image": np.zeros((4, 4), np.float32),
                   "label": np.zeros(4, np.int32)}

    out = evaluate(Zero(), {}, forever(), stat_fn=accuracy, max_batches=5)
    assert out["batches"] == 5 and out["count"] == 20


def test_trained_mlp_beats_chance():
    """End-to-end: train on synthetic MNIST, eval accuracy >> 10% chance."""
    from nezha_tpu.data.mnist import mnist_batches
    from nezha_tpu.train.loop import init_train_state, make_train_step

    def loss(logits, b):
        return ops.softmax_cross_entropy_with_integer_labels(logits, b["label"])

    model = MLP(hidden=(64,))
    opt = optim.momentum(0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, loss)
    it = mnist_batches(64)
    for _ in range(60):
        state, _ = step(state, next(it))
    out = evaluate(model, state["variables"],
                   mnist_batches(64, split="test", epochs=1),
                   stat_fn=accuracy, max_batches=8)
    assert out["accuracy"] > 0.8, out


def test_cli_eval_flag():
    from nezha_tpu.cli.train import build_parser, run

    args = build_parser().parse_args([
        "--config", "mlp_mnist", "--steps", "30", "--batch-size", "64",
        "--platform", "cpu", "--log-every", "10", "--eval",
        "--eval-batches", "4",
    ])
    last = run(args)
    assert "eval_accuracy" in last
    assert last["eval_accuracy"] > 0.3
