"""Tensor-sharded serving (serve/sharded): the M-device engine under a
1xM mesh on the suite's 8 forced host devices.

What this file pins, per ISSUE 14's acceptance:

- greedy outputs BIT-IDENTICAL to the single-device engine across the
  parity suites (paged bf16/f32, int8 + per-block scales, speculative
  decode) at mesh 2;
- the frozen program contract PER MESH — ``1 step +
  len(prefill_buckets)`` executor entries, misses frozen after warmup;
- ``--mesh 4`` serves a config whose KV + params exceed a single
  device's budget, provable from ``memory_report`` /
  ``bytes_resident_per_shard`` accounting;
- train->serve resharding: CRC-verified streaming load, bitwise
  round-trip through ``nezha-reshard``, and the ``serve.reshard``
  chaos drill — a corrupt leaf or injected fault is a typed
  ``ReshardError`` and the engine REFUSES to start;
- seeded chaos at mesh 2 (prefill errors, NaN bursts, KV bind
  failures, replica kill under the router) with zero slot/block/scale
  leaks per shard (``leak_check`` covers sharding loss too);
- migration composes: gather-on-export from a mesh-2 source installs
  bit-identically into a single-device destination;
- the mesh telemetry (``serve.mesh.devices`` gauge,
  ``serve.mesh.collective_bytes`` counter, report ``mesh:`` line) is
  captured schema-clean.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nezha_tpu import faults, obs
from nezha_tpu.faults import FaultPlan
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig
from nezha_tpu.serve.engine import SpeculativeConfig
from nezha_tpu.serve.sharded import (
    ReshardError,
    ShardedEngine,
    reshard_checkpoint,
    save_serve_checkpoint,
    verify_roundtrip,
)

CFG = dict(vocab_size=64, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=32)
SCFG = ServeConfig(max_batch_size=3, max_len=32, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32)
PROMPTS = [[3, 5, 7, 9], [11, 2, 4], [1, 2, 3, 4, 5, 6, 7, 8, 9]]


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def single_engine(model_and_vars):
    model, variables = model_and_vars
    return Engine(model, variables, SCFG)


@pytest.fixture(scope="module")
def mesh2_engine(model_and_vars):
    model, variables = model_and_vars
    return ShardedEngine(model, variables, SCFG, mesh_devices=2)


def _greedy(engine, prompts, max_new=6):
    sched = Scheduler(engine)
    for i, p in enumerate(prompts):
        sched.submit(Request(prompt=p, max_new_tokens=max_new,
                             request_id=f"r{i}"))
    sched.run_until_idle(max_iters=400)
    assert not sched.has_work()
    return {k: v.tokens for k, v in sched.results.items()}


# ----------------------------------------------------- parity + contract
def test_mesh2_greedy_parity_bit_identical(single_engine, mesh2_engine):
    """The headline gate: same weights, same prompts, greedy decode —
    the 2-device tensor-parallel engine emits exactly the single-device
    engine's tokens (attention is head-parallel; the per-proj reduces
    are the only cross-device math)."""
    ref = _greedy(single_engine, PROMPTS)
    got = _greedy(mesh2_engine, PROMPTS)
    assert got == ref
    assert all(v for v in ref.values())


def test_frozen_program_contract_per_mesh(mesh2_engine):
    """Steady state per mesh is exactly ``1 step +
    len(prefill_buckets)`` executor entries with misses FROZEN: more
    traffic through warmed buckets compiles nothing."""
    _greedy(mesh2_engine, PROMPTS)   # warm both buckets + the step
    stats = mesh2_engine.compile_stats()
    assert stats["entries"] == 1 + len(SCFG.prefill_buckets)
    misses0 = stats["misses"]
    _greedy(mesh2_engine, [[7, 7, 7], [9] * 7])
    after = mesh2_engine.compile_stats()
    assert after["entries"] == 1 + len(SCFG.prefill_buckets)
    assert after["misses"] == misses0, "a sharded dispatch recompiled"


def test_mesh2_int8_parity_and_scale_shards(model_and_vars):
    """PR 9's parity suite under the mesh: int8 blocks + per-(block,
    head) scales shard on the head axis; greedy outputs match the
    single-device int8 engine bit for bit, and the per-shard leak
    oracle (books + scale shapes + sharding) stays clean."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, kv_dtype="int8")
    ref = _greedy(Engine(model, variables, cfg), PROMPTS)
    eng = ShardedEngine(model, variables, cfg, mesh_devices=2)
    assert _greedy(eng, PROMPTS) == ref
    eng.pool.leak_check()
    assert eng.pool.bytes_resident_per_shard == 0   # all freed
    sh = eng.pool.caches[0]["k_scale"].sharding
    assert not sh.is_fully_replicated


def test_mesh2_speculative_parity(model_and_vars):
    """PR 13's parity suite under the mesh: the fused
    draft->verify->accept program (draft pool mirrored + head-sharded
    too) emits exactly the classic greedy stream."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(
        SCFG, speculative=SpeculativeConfig(draft_k=2, draft_layers=1))
    ref = _greedy(Engine(model, variables, cfg), PROMPTS[:2])
    eng = ShardedEngine(model, variables, cfg, mesh_devices=2)
    assert _greedy(eng, PROMPTS[:2]) == ref
    eng.pool.leak_check()       # recurses into the mirrored draft pool


def test_mesh2_forced_kernel_parity(model_and_vars):
    """``decode_impl="kernel"`` under the mesh: the raw Mosaic call can
    never be handed to the auto-partitioner, so the force routes
    through the nested-shard_map per-shard kernel (interpret mode on
    CPU) — and stays bit-identical to the single-device forced-kernel
    engine."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, decode_impl="kernel")
    ref = _greedy(Engine(model, variables, cfg), PROMPTS[:2], max_new=4)
    eng = ShardedEngine(model, variables, cfg, mesh_devices=2)
    assert _greedy(eng, PROMPTS[:2], max_new=4) == ref


# ------------------------------------------------- over-budget serving
def test_mesh4_serves_config_over_single_device_budget(model_and_vars):
    """THE scale-axis acceptance: a config whose KV + params exceed a
    hypothetical single-device budget serves on ``--mesh 4`` because
    each shard holds ~1/4 of the bytes — provable from the committed
    arrays' own shard accounting, then actually served."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, max_batch_size=4, max_len=64,
                              kv_num_blocks=None)
    eng = ShardedEngine(model, variables, cfg, mesh_devices=4)
    rep = eng.memory_report()
    assert rep["mesh_devices"] == 4
    # KV divides exactly by 4; params shard except the replicated tail
    # (layernorms, wpe, row-parallel biases).
    assert rep["kv_capacity_bytes_per_device"] * 4 == \
        rep["kv_capacity_bytes"]
    assert rep["params_bytes_per_device"] < rep["params_bytes"]
    # The budget story: a device half the logical footprint cannot
    # hold the model + KV, but each mesh-4 shard fits comfortably.
    budget = rep["bytes_total"] // 2
    assert rep["bytes_total"] > budget
    assert rep["bytes_per_device"] < budget
    # ...and it actually serves.
    out = _greedy(eng, [[5, 17, 3]], max_new=4)
    assert len(out["r0"]) == 4
    # Resident accounting is per-shard exact while a request is live.
    sched = Scheduler(eng)
    sched.submit(Request(prompt=[2, 4, 6, 8], max_new_tokens=4,
                         request_id="live"))
    sched.step()
    assert eng.pool.bytes_resident > 0
    assert eng.pool.bytes_resident_per_shard * 4 == \
        eng.pool.bytes_resident
    sched.run_until_idle(max_iters=100)
    eng.pool.leak_check()


# --------------------------------------------------------- resharding
def _train_ckpt(tmp_path, model, variables, step=5):
    from nezha_tpu import optim
    from nezha_tpu.train.checkpoint import save_checkpoint
    from nezha_tpu.train.loop import init_train_state
    state = init_train_state(model, optim.sgd(0.1),
                             jax.random.PRNGKey(0))
    state["variables"] = variables
    d = str(tmp_path / "ck")
    save_checkpoint(d, state, step)
    return d


def test_reshard_streams_crc_verified_and_roundtrips(model_and_vars,
                                                     tmp_path):
    model, variables = model_and_vars
    ck = _train_ckpt(tmp_path, model, variables)
    from nezha_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"tp": 4}, devices=jax.devices()[:4])
    rv, step = reshard_checkpoint(ck, model, mesh)
    assert step == 5
    for a, b in zip(jax.tree_util.tree_leaves(variables["params"]),
                    jax.tree_util.tree_leaves(rv["params"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # Megatron layout landed: the qkv weight is feature-sharded.
    assert not rv["params"]["h0"]["attn"]["qkv"]["w"] \
        .sharding.is_fully_replicated
    # Bitwise round trip through the serve-topology save.
    out = str(tmp_path / "serve4")
    save_serve_checkpoint(out, rv, step)
    assert verify_roundtrip(out, rv, step) == []
    # ...and the serve-topology save itself reshards (any mesh size).
    mesh2 = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    rv2, _ = reshard_checkpoint(out, model, mesh2)
    for a, b in zip(jax.tree_util.tree_leaves(variables["params"]),
                    jax.tree_util.tree_leaves(rv2["params"])):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_reshard_cli_roundtrip(model_and_vars, tmp_path, capsys):
    model, variables = model_and_vars
    del model, variables
    from nezha_tpu.cli.train import TINY_GPT2_KW
    tiny = GPT2(GPT2Config(**TINY_GPT2_KW))
    ck = _train_ckpt(tmp_path, tiny, tiny.init(jax.random.PRNGKey(1)))
    from nezha_tpu.cli import reshard as cli_reshard
    out = str(tmp_path / "out")
    rc = cli_reshard.main(["--ckpt-dir", ck, "--mesh", "2",
                           "--model-preset", "tiny", "--out", out,
                           "--verify", "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["roundtrip_ok"] is True
    assert report["mesh_devices"] == 2
    assert report["params_bytes_per_device"] < report["params_bytes"]


def test_reshard_refuses_corrupt_and_missing(model_and_vars, tmp_path):
    """The corrupt-checkpoint-at-boot story: a flipped byte fails the
    PR 4 CRC manifest and surfaces as the typed ``ReshardError`` — the
    engine never starts (``nezha-serve --mesh`` maps it to SystemExit)."""
    model, variables = model_and_vars
    ck = _train_ckpt(tmp_path, model, variables)
    from nezha_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    # Corrupt one params leaf, keep the original manifest.
    path = os.path.join(ck, "step_00000005.npz")
    z = np.load(path)
    flat = {k: np.array(z[k]) for k in z.files}
    z.close()
    key = sorted(k for k in flat
                 if k.startswith("variables/params/"))[0]
    flat[key].flat[0] += 1.0
    np.savez(path, **flat)
    with pytest.raises(ReshardError, match="CRC32 mismatch"):
        reshard_checkpoint(ck, model, mesh)
    # Missing checkpoint entirely: typed, not a stack trace.
    with pytest.raises(ReshardError, match="no training checkpoint"):
        reshard_checkpoint(str(tmp_path / "empty"), model, mesh)


def test_serve_reshard_fault_drill(model_and_vars, tmp_path):
    """The pinned ``serve.reshard`` chaos point: an injected error at
    the reshard entry is the SAME typed refusal a corrupt leaf
    produces, end to end through the CLI (engine refuses to start)."""
    model, variables = model_and_vars
    ck = _train_ckpt(tmp_path, model, variables)
    from nezha_tpu.parallel.mesh import make_mesh
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    faults.install(FaultPlan.parse("serve.reshard:error@1"))
    with pytest.raises(ReshardError, match="injected reshard fault"):
        reshard_checkpoint(ck, model, mesh)
    faults.clear()
    # The plan consumed its one shot above; a clean retry succeeds —
    # refusal is fail-stop, not fail-broken.
    rv, _ = reshard_checkpoint(ck, model, mesh)
    assert rv["params"] is not None


# ----------------------------------------------------- chaos at mesh 2
@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_chaos_mesh2_zero_leaks_per_shard(model_and_vars, kv_dtype):
    """PR 6/7/9's chaos oracles re-run under the mesh: seeded prefill
    errors, mid-stream NaN bursts, and KV bind failures against a
    mesh-2 engine — every request retires typed, every slot frees, and
    the per-shard leak check (ref-count books + scale shapes + head
    sharding) balances."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, queue_capacity=16, kv_dtype=(
        "int8" if kv_dtype == "int8" else "bf16"))
    eng = ShardedEngine(model, variables, cfg, mesh_devices=2)
    sched = Scheduler(eng)
    faults.install(FaultPlan.parse(
        "serve.prefill:error@3;serve.step.logits:nan@4;"
        "serve.kv.bind:error@9", seed=7))
    for i in range(10):
        sched.submit(Request(prompt=[(3 + 5 * i) % 64, 2, 9],
                             max_new_tokens=4, request_id=f"c{i}",
                             seed=i))
    sched.run_until_idle(max_iters=600)
    faults.clear()
    assert not sched.has_work()
    assert len(sched.results) == 10
    reasons = {r.finish_reason for r in sched.results.values()}
    assert reasons <= {"length", "error", "eos"}
    assert "error" in reasons            # the plan genuinely fired
    assert eng.pool.num_free == cfg.max_batch_size
    eng.pool.leak_check()
    assert eng.pool.bytes_resident_per_shard == 0


def test_replica_kill_chaos_with_mesh2():
    """PR 6's replica-kill chaos with ``--mesh 2`` workers: two
    thread-hosted replicas, each a 2-device tensor-parallel engine
    behind a real socket; a mid-load kill fails the in-flight request
    over and the supervisor restarts the member — zero silent losses,
    the router blind to the mesh."""
    import threading
    import time

    from nezha_tpu.cli.serve import build_parser
    from nezha_tpu.serve.router import Router
    from nezha_tpu.serve.supervisor import (RouterConfig, Supervisor,
                                            ThreadBackend)
    wargs = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--mesh", "2",
         "--max-batch-size", "2", "--max-len", "48",
         "--max-prefill-len", "8", "--queue-capacity", "4",
         "--platform", "cpu"])
    cfg = RouterConfig(replicas=2, probe_interval_s=0.1, probe_misses=3,
                       route_retries=2, retry_backoff_base_s=0.01,
                       retry_backoff_max_s=0.05,
                       restart_backoff_base_s=0.05,
                       restart_backoff_max_s=0.5,
                       drain_timeout_s=20.0, seed=0)
    sup = Supervisor(ThreadBackend(wargs, drain_timeout_s=20.0), cfg)
    router = Router(sup, cfg)
    sup.start()
    try:
        assert router.wait_live(2, timeout_s=600), sup.describe()
        faults.install(FaultPlan.parse("serve.step:delay=0.05x*"))
        out = {}
        t = threading.Thread(target=lambda: out.update(dict(zip(
            ("code", "obj"),
            router.route({"id": "meshkill", "prompt_tokens": [5, 17, 3],
                          "max_new_tokens": 24})))))
        t.start()
        victim = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            busy = [r.rid for r in sup.replicas() if r.in_flight]
            if busy:
                victim = busy[0]
                break
            time.sleep(0.01)
        assert victim is not None
        time.sleep(0.2)
        sup.kill(victim)
        t.join(timeout=300)
        faults.clear()
        assert out["code"] == 200, out
        assert out["obj"]["finish_reason"] == "length"
        assert router.wait_live(2, timeout_s=600), sup.describe()
    finally:
        faults.clear()
        router.stop()
        sup.shutdown()


# ----------------------------------------------------------- migration
def test_migration_gather_on_export_from_mesh(model_and_vars):
    """Gather-on-export: a parked prompt on a mesh-2 source exports
    the FULL-HEAD int8+scales wire payload (shards gathered on read),
    and a single-device destination installs it — the migrated request
    prefix-hits instead of re-prefilling. The wire format is
    mesh-blind."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, kv_block_size=4)
    src = Scheduler(ShardedEngine(model, variables, cfg,
                                  mesh_devices=2))
    dst = Scheduler(Engine(model, variables, cfg))
    prompt = PROMPTS[2]          # 9 tokens -> 2 full blocks of 4
    src.submit(Request(prompt=prompt, max_new_tokens=4,
                       request_id="mig", prefill_only=True))
    src.run_until_idle(max_iters=50)
    from nezha_tpu.serve import migrate
    tokens, layers, nbytes = migrate.decode_wire(
        src.export_parked("mig"))
    assert len(tokens) == 8 and layers[0]["k"].shape[0] == 2
    # Full heads on the wire regardless of the source mesh.
    assert layers[0]["k"].shape[1] == CFG["num_heads"]
    assert dst.install_migrated(tokens, layers, nbytes) == 2
    assert src.ack_parked("mig")
    hits0 = dst.engine.pool.prefix_hits
    dst.submit(Request(prompt=prompt, max_new_tokens=4,
                       request_id="mig"))
    dst.run_until_idle(max_iters=100)
    assert dst.engine.pool.prefix_hits == hits0 + 1
    src.engine.pool.leak_check()
    dst.engine.pool.leak_check()


# ------------------------------------------------- per-shard kernel
def test_flash_decode_sharded_matches_unsharded_kernel():
    """The nested-shard_map decode kernel (the sharded engine's TPU
    decode path) computes exactly the unsharded kernel's output:
    heads are embarrassingly parallel, so an H/tp slice per device
    with replicated lengths + block tables must be a pure reshard.
    Interpret mode stands in for Mosaic on CPU, same as the rest of
    the kernel parity suite."""
    from nezha_tpu.ops.pallas import (flash_decode_attention,
                                      flash_decode_attention_sharded)
    from nezha_tpu.parallel.mesh import make_mesh

    b, h, d, nblk, bs = 3, 4, 8, 9, 8
    key = jax.random.PRNGKey(0)
    kq, kk, kv2, ks = jax.random.split(key, 4)
    q = jax.random.normal(kq, (b, h, 1, d), jnp.float32)
    kp = jax.random.normal(kk, (nblk, h, bs, d), jnp.float32)
    vp = jax.random.normal(kv2, (nblk, h, bs, d), jnp.float32)
    tables = jax.random.randint(ks, (b, 4), 1, nblk).astype(jnp.int32)
    lengths = jnp.asarray([5, 0, 17], jnp.int32)
    ref = flash_decode_attention(q, kp, vp, lengths,
                                 block_tables=tables, interpret=True)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    got = flash_decode_attention_sharded(q, kp, vp, lengths, mesh,
                                         block_tables=tables,
                                         interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    # Int8 pools: scale rows shard with their heads.
    from nezha_tpu.ops.quant import quantize_kv_block
    kq8, ksc = quantize_kv_block(kp)
    vq8, vsc = quantize_kv_block(vp)
    ref8 = flash_decode_attention(q, kq8, vq8, lengths,
                                  block_tables=tables,
                                  block_scales=(ksc, vsc),
                                  interpret=True)
    got8 = flash_decode_attention_sharded(q, kq8, vq8, lengths, mesh,
                                          block_tables=tables,
                                          block_scales=(ksc, vsc),
                                          interpret=True)
    np.testing.assert_allclose(np.asarray(got8), np.asarray(ref8),
                               rtol=1e-6, atol=1e-6)


# ----------------------------------------------------------- telemetry
def test_mesh_telemetry_capture_and_report(model_and_vars, tmp_path):
    """A mesh-2 serving run's capture is schema-clean and carries the
    new instruments; the rendered report gains the ``mesh:`` line."""
    from nezha_tpu.analysis.telemetry_schema import check_run_dir
    from nezha_tpu.obs.report import render_serving_section
    model, variables = model_and_vars
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir, meta={"kind": "serve_mesh_test"})
    try:
        eng = ShardedEngine(model, variables, SCFG, mesh_devices=2)
        _greedy(eng, PROMPTS[:2])
    finally:
        obs.end_run()
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert summary["gauges"]["serve.mesh.devices"] == 2
    assert summary["counters"]["serve.mesh.collective_bytes"] > 0
    lines = render_serving_section(summary)
    mesh_lines = [l for l in lines if l.strip().startswith("mesh:")]
    assert mesh_lines and "2 devices" in mesh_lines[0]
    # The reshard span is schema-pinned (emitted inside a run).
    from nezha_tpu.analysis.telemetry_schema import PINNED_SPANS
    assert "serve.reshard_s" in PINNED_SPANS
