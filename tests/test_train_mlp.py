"""End-to-end slice: MLP on MNIST, single process (BASELINE.json config 1).
The framework's first full train loop must demonstrably learn."""

import jax
import numpy as np

from nezha_tpu import data, ops, optim
from nezha_tpu.models.mlp import MLP
from nezha_tpu.train.loop import Trainer, init_train_state, make_train_step


def _loss_fn(logits, batch):
    return ops.softmax_cross_entropy_with_integer_labels(logits, batch["label"])


def test_mlp_train_step_reduces_loss():
    model = MLP(hidden=(64, 64))
    opt = optim.momentum(0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, _loss_fn)
    batches = data.mnist_batches(64, seed=0)
    losses = []
    for i, batch in zip(range(60), batches):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_trainer_fit_and_eval():
    model = MLP(hidden=(64,))
    opt = optim.momentum(0.1)
    trainer = Trainer(model, opt, _loss_fn, rng=jax.random.PRNGKey(1),
                      log_every=5)
    trainer.initialize()
    metrics = trainer.fit(data.mnist_batches(64, seed=1), steps=40)
    assert "loss" in metrics and np.isfinite(metrics["loss"])
    # Eval accuracy on synthetic MNIST should beat chance (10%) clearly.
    test_batch = next(data.mnist_batches(256, split="test"))
    logits, _ = model.apply(trainer.state["variables"], test_batch,
                            training=False)
    acc = float(ops.accuracy(logits, test_batch["label"]))
    assert acc > 0.3, acc


def test_mnist_batches_shapes():
    b = next(data.mnist_batches(32))
    assert b["image"].shape == (32, 28, 28)
    assert b["label"].shape == (32,)
    assert b["image"].dtype == np.float32
