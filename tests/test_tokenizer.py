"""Exact-match parity of the network-free tokenizers against the Hugging
Face SLOW tokenizers (pure-python reference implementations) over locally
constructed vocab files — no network, no pretrained downloads.

The BPE vocab/merges are built from a training corpus with a miniature
merge-learning loop so the merge table is realistic (ranks matter); the
WordPiece vocab covers continuations, punctuation, accents, CJK, and
unknown words.
"""

import json
import os

import pytest

from nezha_tpu.data.tokenizer import (GPT2BPETokenizer, WordPieceTokenizer,
                                      _bytes_to_unicode, load_tokenizer)

transformers = pytest.importorskip("transformers")


def _learn_bpe(corpus: str, n_merges: int):
    """Tiny reference BPE learner (GPT-2 style, byte-level): returns
    (vocab dict, merges list) in the on-disk format."""
    import regex

    from collections import Counter

    benc = _bytes_to_unicode()
    pat = regex.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+|"""
        r""" ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+""")
    words = Counter()
    for tok in pat.findall(corpus):
        words[tuple(benc[b] for b in tok.encode("utf-8"))] += 1
    merges = []
    for _ in range(n_merges):
        pairs = Counter()
        for w, c in words.items():
            for i in range(len(w) - 1):
                pairs[(w[i], w[i + 1])] += c
        if not pairs:
            break
        (a, b), _c = pairs.most_common(1)[0]
        merges.append((a, b))
        new_words = Counter()
        for w, c in words.items():
            out, i = [], 0
            while i < len(w):
                if i < len(w) - 1 and w[i] == a and w[i + 1] == b:
                    out.append(a + b)
                    i += 2
                else:
                    out.append(w[i])
                    i += 1
            new_words[tuple(out)] += c
        words = new_words
    vocab = {ch: i for i, ch in enumerate(sorted(benc.values()))}
    for a, b in merges:
        vocab[a + b] = len(vocab)
    return vocab, merges


CORPUS = ("The quick brown fox jumps over the lazy dog. "
          "the theatre of the absurd -- don't stop, it's 1234 times better! "
          "  Multiple   spaces\tand\nnewlines. naive cafe RESUME "
          "hello hello hello world world worlds")

TEXTS = [
    "The quick brown fox",
    "don't stop, it's the theatre!",
    "  leading spaces and   runs   ",
    "numbers 1234 and 99 mix",
    "unseen wordzzz qqq",
    "trailing space ",
    "tabs\tand\nnewlines",
    "punct!!! ... (parens) [brackets]",
    "",
]


@pytest.fixture(scope="module")
def bpe_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("bpe")
    vocab, merges = _learn_bpe(CORPUS, 60)
    (d / "vocab.json").write_text(json.dumps(vocab), encoding="utf-8")
    (d / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges) + "\n",
        encoding="utf-8")
    return str(d)


def test_bpe_matches_hf_slow(bpe_dir):
    ours = GPT2BPETokenizer.from_dir(bpe_dir)
    theirs = transformers.GPT2Tokenizer(
        os.path.join(bpe_dir, "vocab.json"),
        os.path.join(bpe_dir, "merges.txt"))
    for text in TEXTS:
        assert ours.encode(text) == theirs.encode(text), text


def test_bpe_roundtrip(bpe_dir):
    tok = GPT2BPETokenizer.from_dir(bpe_dir)
    for text in TEXTS:
        assert tok.decode(tok.encode(text)) == text
    # Unicode outside the corpus still round-trips (byte fallback).
    text = "café 中文 emoji \U0001f600"
    assert tok.decode(tok.encode(text)) == text


def test_bpe_vocab_size_and_known_merge(bpe_dir):
    tok = GPT2BPETokenizer.from_dir(bpe_dir)
    assert tok.vocab_size >= 256
    # "the" is frequent in CORPUS: must encode to few tokens, and fewer
    # than the byte count (merges actually engaged).
    ids = tok.encode(" the")
    assert len(ids) < 4


WP_VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
            "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
            "over", "lazy", "dog", "un", "##want", "##able", "!", ",", ".",
            "?", "'", "naive", "cafe", "1234", "##9", "99", "hello", "world",
            "resume", "中", "文"]


@pytest.fixture(scope="module")
def wp_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("wp")
    (d / "vocab.txt").write_text("\n".join(WP_VOCAB) + "\n",
                                 encoding="utf-8")
    return str(d)


WP_TEXTS = [
    "The quick brown fox jumps over the lazy dog.",
    "unwanted jumping, unwantable!",
    "naïve café RÉSUMÉ",     # accents fold to vocab words
    "hello 中文 world",                           # CJK chars split out
    "completely unknownword here?",
    "punct' , . !",
    "99 1234",
]


def test_wordpiece_matches_hf_slow(wp_dir):
    ours = WordPieceTokenizer.from_dir(wp_dir)
    theirs = transformers.BertTokenizer(os.path.join(wp_dir, "vocab.txt"))
    for text in WP_TEXTS:
        assert ours.encode(text) == theirs.encode(text), text
        assert ours.tokenize(text) == theirs.tokenize(text), text


def test_wordpiece_pairs_and_segments(wp_dir):
    ours = WordPieceTokenizer.from_dir(wp_dir)
    theirs = transformers.BertTokenizer(os.path.join(wp_dir, "vocab.txt"))
    a, b = "the quick fox", "hello world"
    assert ours.encode(a, b) == theirs.encode(a, b)
    ids, segs = ours.encode_with_segments(a, b)
    enc = theirs(a, b)
    assert ids == enc["input_ids"]
    assert segs == enc["token_type_ids"]


def test_wordpiece_decode_and_mask_id(wp_dir):
    tok = WordPieceTokenizer.from_dir(wp_dir)
    ids = tok.encode("unwanted jumping")
    assert tok.decode(ids) == "unwanted jumping"
    assert tok.mask_token_id == WP_VOCAB.index("[MASK]")


def test_load_tokenizer_autodetect(bpe_dir, wp_dir, tmp_path):
    assert isinstance(load_tokenizer(bpe_dir), GPT2BPETokenizer)
    assert isinstance(load_tokenizer(wp_dir), WordPieceTokenizer)
    with pytest.raises(FileNotFoundError, match="no tokenizer files"):
        load_tokenizer(str(tmp_path))


def test_load_tokenizer_honors_do_lower_case(wp_dir, tmp_path):
    import shutil
    d = tmp_path / "cased"
    d.mkdir()
    shutil.copy(os.path.join(wp_dir, "vocab.txt"), d / "vocab.txt")
    (d / "tokenizer_config.json").write_text(
        json.dumps({"do_lower_case": False}), encoding="utf-8")
    tok = load_tokenizer(str(d))
    assert tok.lowercase is False
    # Cased: "The" is not in vocab -> [UNK]; lowercased version is.
    assert tok.tokenize("The") == ["[UNK]"]


def test_bpe_roundtrip_property(bpe_dir):
    """Property: byte-level BPE round-trips ARBITRARY unicode text (the
    byte fallback guarantees totality), hypothesis-driven."""
    hyp = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    tok = GPT2BPETokenizer.from_dir(bpe_dir)

    @settings(max_examples=200, deadline=None)
    @given(st.text(max_size=80))
    def check(s):
        assert tok.decode(tok.encode(s)) == s

    check()


def test_wordpiece_total_on_arbitrary_text(wp_dir):
    """Property: WordPiece never crashes and never emits out-of-vocab
    tokens on arbitrary input (unknown words collapse to [UNK])."""
    hyp = pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    tok = WordPieceTokenizer.from_dir(wp_dir)

    @settings(max_examples=200, deadline=None)
    @given(st.text(max_size=80))
    def check(s):
        for t in tok.tokenize(s):
            assert t in tok.vocab

    check()


def test_learn_bpe_deterministic_and_consistent():
    """The offline learner produces a tokenizer that (a) is deterministic,
    (b) compresses the training corpus (merges engage), (c) round-trips,
    and (d) exact-matches the HF slow tokenizer over its own files."""
    from nezha_tpu.data.bpe_train import learn_bpe, save_bpe_files

    v1, m1 = learn_bpe([CORPUS], 50)
    v2, m2 = learn_bpe([CORPUS], 50)
    assert m1 == m2 and v1 == v2
    assert len(m1) == 50 and len(v1) == 256 + 50

    tok = GPT2BPETokenizer(v1, m1)
    ids = tok.encode(CORPUS)
    assert len(ids) < len(CORPUS.encode("utf-8"))  # compression happened
    assert tok.decode(ids) == CORPUS
    assert tok.decode(tok.encode("unseen zzz • ©")) == "unseen zzz • ©"


def test_learn_bpe_files_hf_parity(tmp_path):
    from nezha_tpu.data.bpe_train import learn_bpe, save_bpe_files

    v, m = learn_bpe([CORPUS], 40)
    d = tmp_path / "learned"
    save_bpe_files(str(d), v, m)
    ours = GPT2BPETokenizer.from_dir(str(d))
    theirs = transformers.GPT2Tokenizer(str(d / "vocab.json"),
                                        str(d / "merges.txt"))
    for text in TEXTS:
        assert ours.encode(text) == theirs.encode(text), text


def test_pack_text_learn_bpe_cli(tmp_path):
    """nezha-pack-text --learn-bpe end-to-end: learn from the corpus, pack
    with the learned vocabulary, round-trip the packed ids to text."""
    from nezha_tpu.cli.pack_text import build_parser, run
    import numpy as np

    src = tmp_path / "corpus.txt"
    src.write_text(CORPUS, encoding="utf-8")
    out = tmp_path / "train.tokens.u16"
    tokdir = tmp_path / "tok"
    res = run(build_parser().parse_args(
        [str(src), "--learn-bpe", "30", "--save-tokenizer", str(tokdir),
         "--out", str(out)]))
    assert res["tokens"] > 0
    tok = load_tokenizer(str(tokdir))
    ids = np.fromfile(out, np.uint16).tolist()
    assert tok.decode(ids) == CORPUS + "\n"
    with pytest.raises(SystemExit, match="save-tokenizer"):
        run(build_parser().parse_args(
            [str(src), "--learn-bpe", "10", "--out", str(out)]))


def test_learn_wordpiece_total_and_deterministic():
    """The learned WordPiece vocab tokenizes its own training corpus with
    ZERO [UNK] (char fallback guarantees totality), merges engage, and
    the output is deterministic."""
    from nezha_tpu.data.bpe_train import learn_wordpiece

    v1 = learn_wordpiece([CORPUS], 160)
    v2 = learn_wordpiece([CORPUS], 160)
    assert v1 == v2
    assert v1[:5] == ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    # The corpus may exhaust its merges before the target size.
    assert 50 < len(v1) <= 160
    assert any(t.startswith("##") and len(t) > 3 for t in v1)  # merges

    tok = WordPieceTokenizer({t: i for i, t in enumerate(v1)})
    pieces = tok.tokenize(CORPUS)
    assert "[UNK]" not in pieces
    # Compression vs pure chars: words collapse into multi-char pieces.
    n_chars = sum(len(w) for w in tok._basic(CORPUS))
    assert len(pieces) < n_chars


def test_pack_text_learn_wordpiece_cli_and_mlm_train(tmp_path):
    """Airgapped BERT data prep end-to-end: learn WordPiece -> pack ->
    dynamic-MLM train through the real CLI (mask id 4 = [MASK] passed
    explicitly; ids are real subwords, not bytes)."""
    from nezha_tpu.cli.pack_text import build_parser as pp, run as pack_run
    from nezha_tpu.cli.train import build_parser as tp, run as train_run
    import pytest

    try:
        from nezha_tpu.data.native import load_library
        load_library()
    except Exception:
        pytest.skip("native runtime not available")

    src = tmp_path / "corpus.txt"
    src.write_text(CORPUS * 30, encoding="utf-8")
    out = tmp_path / "train.tokens.u16"
    tokdir = tmp_path / "tok"
    res = pack_run(pp().parse_args(
        [str(src), "--learn-wordpiece", "200", "--save-tokenizer",
         str(tokdir), "--out", str(out)]))
    assert res["tokens"] > 500
    tok = load_tokenizer(str(tokdir))
    assert tok.mask_token_id == 4
    m = train_run(tp().parse_args(
        ["--config", "bert_base_zero1", "--model-preset", "tiny",
         "--steps", "2", "--batch-size", "8", "--log-every", "1",
         "--mlm-mask-token", str(tok.mask_token_id),
         "--data-dir", str(tmp_path)]))
    import numpy as np
    assert np.isfinite(m["loss"])


def test_wordpiece_missing_specials_rejected_at_construction(tmp_path):
    """A vocab.txt without the BERT specials (e.g. a --learn-bpe vocab
    pointed at by a BERT flow) is refused at load time with the filename,
    not a bare KeyError mid-encode (ADVICE r5)."""
    bad = tmp_path / "vocab.txt"
    bad.write_text("hello\nworld\n##ld\n", encoding="utf-8")
    with pytest.raises(ValueError, match=r"vocab\.txt.*\[UNK\]"):
        WordPieceTokenizer.from_files(str(bad))
    # [MASK] is lazy: a GPT-style flow without it loads fine, but the MLM
    # accessor diagnoses instead of KeyError-ing.
    ok = tmp_path / "ok"
    ok.mkdir()
    (ok / "vocab.txt").write_text(
        "[PAD]\n[UNK]\n[CLS]\n[SEP]\nhello\n##world\n", encoding="utf-8")
    tok = WordPieceTokenizer.from_dir(str(ok))
    assert tok.encode("hello", add_special_tokens=False) == [4]
    with pytest.raises(ValueError, match=r"\[MASK\]"):
        _ = tok.mask_token_id


def test_bpe_mismatched_vocab_merges_rejected(tmp_path):
    """A merges.txt whose outputs are missing from vocab.json (files from
    two different tokenizers) is refused at construction naming both
    files, instead of a bare KeyError mid-encode (ADVICE r5)."""
    vocab, merges = _learn_bpe(CORPUS, 20)
    d = tmp_path / "tok"
    d.mkdir()
    # Drop every merged token from the vocab: chars only = a vocab that
    # never saw these merges.
    chars_only = {k: v for k, v in vocab.items()
                  if all(k != a + b for a, b in merges)}
    (d / "vocab.json").write_text(json.dumps(chars_only), encoding="utf-8")
    (d / "merges.txt").write_text(
        "\n".join(f"{a} {b}" for a, b in merges) + "\n", encoding="utf-8")
    with pytest.raises(ValueError, match=r"merges\.txt does not match"):
        GPT2BPETokenizer.from_dir(str(d))
