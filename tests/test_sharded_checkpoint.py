"""Sharding-aware checkpointing: per-shard save, per-shard restore into a
live sharded layout, resharding restore, torn-save detection, async saves.
The headline property (VERDICT round 1 item 7): saving/restoring ZeRO-1
optimizer state never materializes the full state on one host."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import optim, parallel
from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss
from nezha_tpu.train import sharded_checkpoint as sc


def tiny_bert():
    return Bert(BertConfig(vocab_size=128, max_positions=32, num_layers=1,
                           num_heads=2, hidden_size=32))


def zero1_state(mesh, seed=1):
    model = tiny_bert()
    opt = optim.adamw(1e-3)
    variables = model.init(jax.random.PRNGKey(seed))
    return model, opt, {
        "variables": parallel.replicate(mesh, variables),
        "opt_state": parallel.zero1_init_opt_state(
            opt, variables["params"], mesh),
        "rng": parallel.replicate(mesh, jax.random.PRNGKey(seed + 1)),
    }


def trees_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_zero1_roundtrip_is_per_shard(devices8, tmp_path):
    mesh = parallel.make_mesh({"dp": 8})
    model, opt, state = zero1_state(mesh)
    # Run one real step so the saved state isn't just init values.
    step = parallel.make_zero1_train_step(model, opt, mlm_loss, mesh)
    from nezha_tpu import data
    batch = parallel.shard_batch(mesh, next(data.synthetic_mlm_batches(
        16, seq_len=16, vocab_size=128)))
    state, _ = step(state, batch)

    sc.save_sharded(tmp_path, state, step=7)

    # On-disk proof of per-shard layout: each ZeRO-1 stat leaf is stored as
    # 8 pieces of 1/8 the (padded) global size, not one full array.
    import json
    d = tmp_path / "step_00000007.sharded"
    meta = json.loads((d / "meta_p0.json").read_text())
    mu_keys = [k for k in meta["leaves"] if "opt_state/mu" in k]
    assert mu_keys
    for k in mu_keys:
        info = meta["leaves"][k]
        n = info["shape"][0]
        assert len(info["shards"]) == 8
        sizes = [se[0][1] - se[0][0] for se in
                 (s["index"] for s in info["shards"])]
        assert all(s == n // 8 for s in sizes)

    # Restore into a fresh sharded template; layout AND values must match.
    _, _, template = zero1_state(mesh, seed=9)
    restored, got_step = sc.restore_sharded(tmp_path, template)
    assert got_step == 7
    trees_equal(restored, state)
    for t, r in zip(jax.tree_util.tree_leaves(template),
                    jax.tree_util.tree_leaves(restored)):
        if isinstance(t, jax.Array):
            assert r.sharding.is_equivalent_to(t.sharding, t.ndim)


def test_restore_never_reads_full_sharded_leaf(devices8, tmp_path, monkeypatch):
    """The restore path must only request per-device slices of sharded
    leaves — no single-host materialization of the full optimizer state."""
    mesh = parallel.make_mesh({"dp": 8})
    _, _, state = zero1_state(mesh)
    sc.save_sharded(tmp_path, state, step=0)

    requested = []
    orig_read = sc._ShardStore.read

    def spy(self, key, index):
        want = [sl.indices(dim)[:2]
                for sl, dim in zip(index, self.leaves[key]["shape"])]
        requested.append((key, want, self.leaves[key]["shape"]))
        return orig_read(self, key, index)

    monkeypatch.setattr(sc._ShardStore, "read", spy)
    _, _, template = zero1_state(mesh, seed=3)
    sc.restore_sharded(tmp_path, template)

    mu_reads = [(want, shape) for key, want, shape in requested
                if "opt_state/mu" in key]
    assert mu_reads
    for want, shape in mu_reads:
        read_n = want[0][1] - want[0][0]
        assert read_n == shape[0] // 8  # slice, never the full leaf


def test_reshard_on_restore(devices8, tmp_path):
    # Save under dp=8, restore onto a dp=4 mesh (different shard sizes):
    # the callback assembles each dp=4 slice from two stored dp=8 shards.
    mesh8 = parallel.make_mesh({"dp": 8})
    _, _, state = zero1_state(mesh8)
    sc.save_sharded(tmp_path, state, step=1)

    mesh4 = parallel.make_mesh({"dp": 4}, devices=jax.devices()[:4])
    _, _, template = zero1_state(mesh4, seed=5)
    # dp=8 padding differs from dp=4 padding for some leaves; restore the
    # equally-padded ones (shape check guards the rest).
    sub = {"variables": template["variables"], "rng": template["rng"]}
    saved_sub = {"variables": state["variables"], "rng": state["rng"]}
    restored, _ = sc.restore_sharded(tmp_path, sub)
    trees_equal(restored, saved_sub)


def test_torn_save_is_ignored(devices8, tmp_path):
    mesh = parallel.make_mesh({"dp": 8})
    _, _, state = zero1_state(mesh)
    sc.save_sharded(tmp_path, state, step=2)
    sc.save_sharded(tmp_path, state, step=5)
    # Tear the newer checkpoint: missing commit marker.
    (tmp_path / "step_00000005.sharded" / "COMPLETE_p0").unlink()
    assert sc.latest_step(tmp_path) == 2


def test_async_checkpointer_roundtrip(devices8, tmp_path):
    mesh = parallel.make_mesh({"dp": 8})
    _, _, state = zero1_state(mesh)
    ck = sc.AsyncCheckpointer()
    ck.save(tmp_path, state, step=3)
    ck.wait()
    _, _, template = zero1_state(mesh, seed=11)
    restored, got = sc.restore_sharded(tmp_path, template)
    assert got == 3
    trees_equal(restored, state)


def test_async_save_overlaps_training_steps(devices8, tmp_path, monkeypatch):
    """With the AsyncCheckpointer as the Trainer's save_fn, step N+1 runs
    while step N's files are still being written (VERDICT r2 missing #7)."""
    import threading
    import time

    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.ops import softmax_cross_entropy_with_integer_labels as ce
    from nezha_tpu.train.loop import Trainer

    write_started = threading.Event()
    write_release = threading.Event()
    real_write = sc._write_prefetched

    def gated_write(ckpt_dir, host_state, step):
        write_started.set()
        assert write_release.wait(timeout=30), "test never released the write"
        return real_write(ckpt_dir, host_state, step)

    monkeypatch.setattr(sc, "_write_prefetched", gated_write)
    ck = sc.AsyncCheckpointer()

    from nezha_tpu import data, optim
    model, opt = MLP(hidden=(16,)), optim.sgd(0.1)
    steps_done = []
    trainer = Trainer(model, opt,
                      lambda logits, b: ce(logits, b["label"]),
                      checkpoint_dir=str(tmp_path), checkpoint_every=1,
                      log_every=0, save_fn=ck.save, save_wait=ck.wait)
    base_fit = trainer.step_fn

    def recording_step(state, batch):
        steps_done.append(time.perf_counter())
        return base_fit(state, batch)

    trainer.step_fn = recording_step
    batches = data.mnist_batches(16, seed=0)

    done = threading.Event()

    def run():
        trainer.fit(batches, 3)
        done.set()

    t = threading.Thread(target=run)
    t.start()
    try:
        # Step 1's save blocks in gated_write; step 2 must still run (its
        # own save then queues behind the in-flight write — one at a time).
        assert write_started.wait(timeout=30)
        deadline = time.time() + 30
        while len(steps_done) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert len(steps_done) >= 2, "training stalled behind the async save"
    finally:
        write_release.set()
        t.join(timeout=60)
    assert done.is_set()
    ck.wait()
    # Every cadence save committed (save() serializes: one in flight).
    assert sc.latest_step(tmp_path) == 3


def test_bfloat16_leaves_roundtrip(devices8, tmp_path):
    # Extension dtypes (kind 'V') are stored as uint views; a straight
    # np.savez would persist void bytes that fail to cast on restore.
    mesh = parallel.make_mesh({"dp": 8})
    state = {
        "w": parallel.replicate(mesh, jnp.arange(16, dtype=jnp.bfloat16)),
        "v": jax.device_put(
            jnp.arange(32, dtype=jnp.bfloat16),
            jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("dp"))),
    }
    sc.save_sharded(tmp_path, state, step=0)
    restored, _ = sc.restore_sharded(tmp_path, state)
    assert restored["w"].dtype == jnp.bfloat16
    trees_equal(restored, state)


def test_missing_leaf_and_shape_mismatch_raise(devices8, tmp_path):
    mesh = parallel.make_mesh({"dp": 8})
    _, _, state = zero1_state(mesh)
    sc.save_sharded(tmp_path, state, step=0)
    _, _, template = zero1_state(mesh, seed=2)
    template["extra"] = jnp.zeros(3)
    with pytest.raises(KeyError, match="extra"):
        sc.restore_sharded(tmp_path, template)
    del template["extra"]
    template["rng"] = jnp.zeros((7,), jnp.uint32)
    with pytest.raises(ValueError, match="shape mismatch"):
        sc.restore_sharded(tmp_path, template)


def test_sharded_keep_last_counts_only_complete(devices8, tmp_path):
    """Retention prunes to the N newest FULLY-COMPLETE sharded checkpoints;
    torn dirs are neither counted nor trusted as fallbacks."""
    import pathlib

    from nezha_tpu import parallel
    from nezha_tpu.train import sharded_checkpoint as sckpt

    mesh = parallel.make_mesh({"dp": 8})
    state = {"w": parallel.replicate(mesh, jnp.arange(8.0))}
    for step in (1, 2):
        sckpt.save_sharded(tmp_path, state, step, keep_last=2)
    # A torn dir (no COMPLETE markers) between complete saves.
    torn = pathlib.Path(tmp_path) / "step_00000003.sharded"
    torn.mkdir()
    (torn / "meta_p0.json").write_text('{"leaves": {}, "world": 1}')
    sckpt.save_sharded(tmp_path, state, 4, keep_last=2)
    names = sorted(p.name for p in pathlib.Path(tmp_path).glob("*.sharded"))
    # keep_last=2 complete saves (2, 4); torn 3 untouched; 1 pruned.
    assert names == ["step_00000002.sharded", "step_00000003.sharded",
                     "step_00000004.sharded"]
    restored, step = sckpt.try_restore_sharded(tmp_path, state)
    assert step == 4


def test_ep_sharded_expert_leaves_restore_bit_exact(devices8, tmp_path):
    """Reshard-on-restore of the MoE layout: [E,.,.] expert leaves split
    over ep must come back VALUE-exact (a shard-to-rank permutation would
    keep shapes and finiteness — only a leafwise compare catches it)."""
    import jax

    from nezha_tpu import optim, parallel
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    from nezha_tpu.parallel.expert import gpt2_moe_gspmd_rules
    from nezha_tpu.train import sharded_checkpoint as sckpt
    from nezha_tpu.train.loop import init_train_state

    cfg = GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                     num_heads=2, hidden_size=32, moe_experts=4)
    model = GPT2(cfg)
    opt = optim.adamw(1e-3)
    mesh = parallel.make_mesh({"dp": 2, "tp": 2, "ep": 2})
    rules = gpt2_moe_gspmd_rules(parallel.GPT2_TP_RULES)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    specs = parallel.param_specs_from_rules(
        state["variables"]["params"], rules, strict=True)
    state = parallel.shard_train_state(state, mesh, specs)
    want = jax.tree_util.tree_map(np.asarray, jax.device_get(state))

    sckpt.save_sharded(tmp_path, state, 7)
    template = parallel.shard_train_state(
        init_train_state(model, opt, jax.random.PRNGKey(1)), mesh, specs)
    restored, step = sckpt.try_restore_sharded(tmp_path, template)
    assert step == 7
    got = jax.tree_util.tree_map(np.asarray, jax.device_get(restored))
    jax.tree_util.tree_map(np.testing.assert_array_equal, want, got)
    # The expert stacks really are ep-split in the restored layout.
    w_in = restored["variables"]["params"]["h1"]["mlp"]["w_in"]
    assert {s.data.shape[0] for s in w_in.addressable_shards} == {2}  # 4/ep=2
