"""Mixed-precision tests: policies, dynamic loss scale, SAME avg_pool."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import nn
from nezha_tpu.tensor import bf16_policy
from nezha_tpu.train.mixed_precision import DynamicLossScale, NoOpLossScale


def test_noop_loss_scale():
    ls = NoOpLossScale()
    grads = {"w": jnp.ones(3)}
    g, ls2, finite = ls.adjust(grads)
    assert bool(finite)
    np.testing.assert_array_equal(np.asarray(g["w"]), 1.0)


def test_dynamic_loss_scale_halves_on_overflow():
    ls = DynamicLossScale(scale_value=jnp.float32(1024.0))
    bad = {"w": jnp.array([jnp.inf])}
    _, ls2, finite = ls.adjust(bad)
    assert not bool(finite)
    assert float(ls2.scale_value) == 512.0
    # Counter resets on overflow.
    assert int(ls2.counter) == 0


def test_dynamic_loss_scale_grows_after_interval():
    ls = DynamicLossScale(scale_value=jnp.float32(8.0), growth_interval=2)
    g = {"w": jnp.array([8.0])}  # scaled grad
    g1, ls, f1 = ls.adjust(g)
    np.testing.assert_allclose(np.asarray(g1["w"]), [1.0])  # unscaled
    _, ls, _ = ls.adjust(g)
    assert float(ls.scale_value) == 16.0  # doubled after 2 clean steps


def test_loss_scale_is_pytree():
    ls = DynamicLossScale()
    leaves = jax.tree_util.tree_leaves(ls)
    assert len(leaves) == 2  # scale + counter thread through jit


def test_loss_scale_scale_unscale_roundtrip():
    ls = DynamicLossScale(scale_value=jnp.float32(64.0))
    loss = jnp.float32(2.0)
    assert float(ls.scale(loss)) == 128.0
    g = ls.unscale({"w": jnp.array([64.0])})
    np.testing.assert_allclose(np.asarray(g["w"]), [1.0])


def test_avg_pool_same_divides_by_true_count():
    x = jnp.ones((1, 4, 4, 1))
    y = nn.avg_pool(x, 3, 1, "SAME")
    # All-ones input: correct SAME average pooling returns exactly 1 even at
    # corners (4-element windows), not 4/9.
    np.testing.assert_allclose(np.asarray(y), 1.0, rtol=1e-6)


def test_bf16_policy_casts():
    p = bf16_policy()
    assert p.cast_to_compute(jnp.ones(2, jnp.float32)).dtype == jnp.bfloat16
    assert p.cast_to_param(jnp.ones(2, jnp.bfloat16)).dtype == jnp.float32
