"""GSPMD dp×tp tests: spec rules hit the right leaves, the sharded step
matches single-device numerics, params actually land sharded."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nezha_tpu import optim, parallel
from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
from nezha_tpu.train.loop import init_train_state, make_train_step


def tiny_gpt2():
    return GPT2(GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                           num_heads=4, hidden_size=32))


def test_param_specs_rules():
    model = tiny_gpt2()
    params = model.init(jax.random.PRNGKey(0))["params"]
    specs = parallel.param_specs_from_rules(params, parallel.GPT2_TP_RULES)
    assert specs["h0"]["attn"]["qkv"]["w"] == P(None, "tp")
    assert specs["h0"]["attn"]["proj"]["w"] == P("tp", None)
    assert specs["h1"]["mlp"]["fc"]["b"] == P("tp")
    assert specs["wte"]["embedding"] == P("tp", None)
    assert specs["ln_f"]["scale"] == P()
    assert specs["wpe"]["embedding"] == P()


def test_strict_rules_cover_gpt2_and_bert():
    # The shipped tables fully enumerate their models (incl. the
    # deliberately-replicated tail), so strict mode passes.
    from nezha_tpu.models.bert import Bert, BertConfig
    gpt2 = tiny_gpt2().init(jax.random.PRNGKey(0))["params"]
    parallel.param_specs_from_rules(gpt2, parallel.GPT2_TP_RULES, strict=True)
    bert = Bert(BertConfig(vocab_size=128, max_positions=32, num_layers=1,
                           num_heads=2, hidden_size=32)).init(
        jax.random.PRNGKey(0))["params"]
    parallel.param_specs_from_rules(bert, parallel.BERT_TP_RULES, strict=True)


def test_auto_partitioner_flag_set_during_gspmd_trace(devices8):
    """Models consult under_auto_partitioner() to avoid auto-choosing
    Pallas kernels inside jit-with-shardings (Mosaic custom calls cannot
    be SPMD-auto-partitioned)."""
    from nezha_tpu.parallel.gspmd import under_auto_partitioner

    seen = []

    class Probe:
        def init(self, rng):
            return {"params": {"w": jnp.ones((4, 4))}, "state": {}}

        def apply(self, variables, batch, training=False, rng=None):
            seen.append(under_auto_partitioner())
            return batch["x"] @ variables["params"]["w"], {}

    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    model = Probe()
    opt = optim.sgd(1e-2)
    state = {"variables": model.init(None), "opt_state": opt.init(
        model.init(None)["params"]), "rng": jax.random.PRNGKey(0)}
    specs = {"w": P(None, "tp")}
    state = parallel.shard_train_state(state, mesh, specs)
    step = parallel.make_gspmd_train_step(
        model, opt, lambda out, b: (out ** 2).mean(), mesh, specs,
        donate=False)
    assert under_auto_partitioner() is False
    step(state, parallel.gspmd.shard_batch_gspmd(
        mesh, {"x": jnp.ones((2, 4))}))
    assert seen == [True]  # set during trace, only there
    assert under_auto_partitioner() is False


def test_strict_rules_fail_loudly():
    import pytest
    params = tiny_gpt2().init(jax.random.PRNGKey(0))["params"]
    # A renamed layer (rule no longer matches anything + param uncovered).
    params["h0"]["attn"]["qkv_renamed"] = params["h0"]["attn"].pop("qkv")
    with pytest.raises(ValueError, match="qkv_renamed"):
        parallel.param_specs_from_rules(params, parallel.GPT2_TP_RULES,
                                        strict=True)
    # An obsolete rule matching nothing also fails.
    with pytest.raises(ValueError, match="matching no parameter"):
        parallel.param_specs_from_rules(
            {"w": jnp.zeros((2, 2))},
            [(r"^w$", P(None, "tp")), (r"^gone$", P("tp"))], strict=True)


def test_gspmd_step_matches_single_device(devices8):
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    model = tiny_gpt2()
    opt = optim.adamw(1e-3, weight_decay=0.0)

    state0 = init_train_state(model, opt, jax.random.PRNGKey(0))
    batch = {"tokens": jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (8, 17)), jnp.int32)}

    # Single device reference.
    ref_step = make_train_step(model, opt, lm_loss, donate=False)
    ref_state, ref_m = ref_step(jax.tree_util.tree_map(jnp.copy, state0), batch)

    # dp=2 x tp=4 GSPMD.
    specs = parallel.param_specs_from_rules(
        state0["variables"]["params"], parallel.GPT2_TP_RULES)
    sharded = parallel.shard_train_state(state0, mesh, specs)
    step = parallel.make_gspmd_train_step(model, opt, lm_loss, mesh, specs,
                                          donate=False)
    new_state, m = step(sharded, parallel.gspmd.shard_batch_gspmd(mesh, batch))

    np.testing.assert_allclose(float(ref_m["loss"]), float(m["loss"]),
                               rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(ref_state["variables"]["params"]),
                    jax.tree_util.tree_leaves(new_state["variables"]["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=5e-5)


def test_gspmd_params_are_physically_sharded(devices8):
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    model = tiny_gpt2()
    opt = optim.adamw(1e-3)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    specs = parallel.param_specs_from_rules(
        state["variables"]["params"], parallel.GPT2_TP_RULES)
    sharded = parallel.shard_train_state(state, mesh, specs)
    qkv_w = sharded["variables"]["params"]["h0"]["attn"]["qkv"]["w"]
    # (32, 96) sharded over tp=4 on dim 1 -> local (32, 24) per device.
    shapes = {s.data.shape for s in qkv_w.addressable_shards}
    assert shapes == {(32, 24)}
    # Optimizer stats follow the param layout (mu of qkv/w also sharded).
    mu = sharded["opt_state"]["mu"]["h0"]["attn"]["qkv"]["w"]
    assert {s.data.shape for s in mu.addressable_shards} == {(32, 24)}


def test_opt_state_specs_recurse_into_wrapped_optimizers(devices8):
    """accumulate_gradients nests the inner optimizer's state under
    "inner"; its mu/nu must inherit the param specs (sharded), not fall to
    a replicate-everything branch (found via --grad-accum x pp review)."""
    from jax.sharding import PartitionSpec as P

    from nezha_tpu import optim
    from nezha_tpu.parallel.gspmd import opt_state_specs

    params = {"w": jnp.zeros((8, 4)), "b": jnp.zeros((4,))}
    param_specs = {"w": P("dp", None), "b": P()}
    opt = optim.accumulate_gradients(optim.adamw(1e-3), 4)
    specs = opt_state_specs(opt.init(params), param_specs)
    assert specs["acc"] == param_specs
    assert specs["count"] == P()
    assert specs["inner"]["mu"] == param_specs  # sharded, not replicated
    assert specs["inner"]["nu"] == param_specs
    assert specs["inner"]["step"] == P()


def test_gspmd_tp_flash_shmap_matches_single(devices8):
    """attn_impl='flash_shmap': the flash kernel runs device-locally over
    tp-sharded heads via a NESTED shard_map inside the gspmd jit (the
    auto-partitioner never sees the Mosaic call) — step-for-step parity
    with single-device composed attention. On TPU, 'auto' selects this
    automatically when tp divides the heads."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from nezha_tpu import optim, parallel
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.parallel.gspmd import shard_batch_gspmd
    from nezha_tpu.train.loop import init_train_state, make_train_step

    kw = dict(vocab_size=128, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=32, fused_loss_chunk=-1)
    toks = np.random.RandomState(0).randint(0, 128, (8, 17)).astype(np.int32)
    batch = {"tokens": jnp.asarray(toks)}

    m0 = GPT2(GPT2Config(attn_impl="xla", **kw))
    opt = optim.adamw(1e-2, weight_decay=0.0)
    s0 = init_train_state(m0, opt, jax.random.PRNGKey(0))
    step0 = make_train_step(m0, opt, lm_loss)
    l0 = []
    for _ in range(3):
        s0, met = step0(s0, batch)
        l0.append(float(met["loss"]))

    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    m1 = GPT2(GPT2Config(attn_impl="flash_shmap", **kw))
    s1 = init_train_state(m1, opt, jax.random.PRNGKey(0))
    specs = parallel.param_specs_from_rules(
        s1["variables"]["params"], parallel.GPT2_TP_RULES, strict=True)
    s1 = parallel.shard_train_state(s1, mesh, specs)
    step1 = parallel.make_gspmd_train_step(m1, opt, lm_loss, mesh, specs)
    b1 = shard_batch_gspmd(mesh, batch)
    l1 = []
    for _ in range(3):
        s1, met = step1(s1, b1)
        l1.append(float(met["loss"]))
    np.testing.assert_allclose(l1, l0, rtol=1e-3)


def test_gspmd_bert_tp_flash_shmap_varlen_matches_single(devices8):
    """BERT's bidirectional flash kernel under GSPMD TP via the nested
    shard_map — INCLUDING dp-sharded kv_lengths right-padding — matches
    single-device composed attention step-for-step."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from nezha_tpu import optim, parallel
    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss
    from nezha_tpu.parallel.gspmd import shard_batch_gspmd
    from nezha_tpu.train.loop import init_train_state, make_train_step

    kw = dict(vocab_size=128, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=32, fused_loss_chunk=-1)
    rs = np.random.RandomState(0)
    batch = {"tokens": jnp.asarray(rs.randint(0, 128, (8, 16)), jnp.int32),
             "labels": jnp.asarray(
                 np.where(rs.rand(8, 16) < 0.3,
                          rs.randint(0, 128, (8, 16)), -100), jnp.int32),
             "kv_lengths": jnp.asarray([16, 12, 16, 9, 16, 16, 5, 16],
                                       jnp.int32)}

    m0 = Bert(BertConfig(attn_impl="xla", **kw))
    opt = optim.adamw(1e-2, weight_decay=0.0)
    s0 = init_train_state(m0, opt, jax.random.PRNGKey(0))
    step0 = make_train_step(m0, opt, mlm_loss)
    l0 = []
    for _ in range(3):
        s0, met = step0(s0, batch)
        l0.append(float(met["loss"]))

    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    m1 = Bert(BertConfig(attn_impl="flash_shmap", **kw))
    s1 = init_train_state(m1, opt, jax.random.PRNGKey(0))
    specs = parallel.param_specs_from_rules(
        s1["variables"]["params"], parallel.BERT_TP_RULES, strict=True)
    s1 = parallel.shard_train_state(s1, mesh, specs)
    step1 = parallel.make_gspmd_train_step(m1, opt, mlm_loss, mesh, specs)
    b1 = shard_batch_gspmd(mesh, batch)
    l1 = []
    for _ in range(3):
        s1, met = step1(s1, b1)
        l1.append(float(met["loss"]))
    np.testing.assert_allclose(l1, l0, rtol=1e-3)


def test_gspmd_pallas_ln_nested_shmap_matches_xla(devices8, monkeypatch):
    """Under the auto-partitioner with a mesh, the fused Pallas LN runs
    device-locally via a nested shard_map (NEZHA_LN_INTERPRET exercises
    the kernel in interpret mode off-TPU) — numerics match the composed
    LN."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from nezha_tpu import nn, parallel
    from nezha_tpu.parallel.gspmd import auto_partitioner_scope

    monkeypatch.setenv("NEZHA_LN_INTERPRET", "1")
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    ln_p = nn.LayerNorm(32, impl="pallas")
    ln_x = nn.LayerNorm(32, impl="xla")
    v = ln_x.init(jax.random.PRNGKey(0))
    v["params"]["scale"] = jnp.asarray(
        np.random.RandomState(1).rand(32).astype(np.float32))
    x = jnp.asarray(np.random.RandomState(0).randn(8, 16, 32)
                    .astype(np.float32))

    with auto_partitioner_scope(mesh):
        y_p, _ = ln_p.apply(v, x)
    y_x, _ = ln_x.apply(v, x)
    np.testing.assert_allclose(np.asarray(y_p), np.asarray(y_x),
                               rtol=2e-5, atol=2e-6)
