"""MoE layer + expert parallelism: routing correctness against a per-token
reference loop, capacity semantics, and ep-sharded execution."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import parallel
from nezha_tpu.parallel.expert import (
    MoE, MoEConfig, dryrun_moe_step, shard_moe_params, _top_k_gating,
)


def _ref_moe(params, x, cfg, capacity):
    """Per-token Python reference: same top-k + capacity-drop semantics."""
    b, s, d = x.shape
    tokens = np.asarray(x, np.float64).reshape(b * s, d)
    rw = np.asarray(params["router"]["w"], np.float64)
    logits = tokens @ rw
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)

    w_in = np.asarray(params["w_in"], np.float64)
    w_out = np.asarray(params["w_out"], np.float64)

    # Assignment order matches _top_k_gating: all top-1 picks first (in token
    # order), then all top-2 picks.
    counts = np.zeros(cfg.num_experts, np.int64)
    y = np.zeros_like(tokens)
    picks = []  # (k, t, e, gate)
    masked = probs.copy()
    for k in range(cfg.top_k):
        idx = masked.argmax(-1)
        for t in range(tokens.shape[0]):
            picks.append((k, t, idx[t], probs[t, idx[t]]))
            masked[t, idx[t]] = -1.0
    for k, t, e, gate in sorted(picks):
        if counts[e] < capacity:
            counts[e] += 1
            h = np.tanh(np.sqrt(2 / np.pi) * (tokens[t] @ w_in[e]) *
                        (1 + 0.044715 * (tokens[t] @ w_in[e]) ** 2))
            gelu = 0.5 * (tokens[t] @ w_in[e]) * (1 + h)
            y[t] += gate * (gelu @ w_out[e])
    return y.reshape(b, s, d)


def test_moe_matches_reference_loop():
    cfg = MoEConfig(d_model=8, d_ff=16, num_experts=4, top_k=2,
                    capacity_factor=8.0)  # capacity large: no drops
    layer = MoE(cfg)
    variables = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 8))
    y, state = layer.apply(variables, x)
    ref = _ref_moe(variables["params"], x, cfg,
                   layer.capacity(x.shape[0] * x.shape[1]))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)
    assert float(state["aux_loss"]) > 0


def test_moe_capacity_drops_tokens():
    """With capacity 1 per expert, most tokens are dropped -> output far
    smaller in norm than with ample capacity."""
    big = MoEConfig(d_model=8, d_ff=16, num_experts=2, top_k=1,
                    capacity_factor=16.0)
    layer_big = MoE(big)
    variables = layer_big.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 8))

    y_big, _ = layer_big.apply(variables, x)

    small = MoEConfig(d_model=8, d_ff=16, num_experts=2, top_k=1,
                      capacity_factor=0.125)  # cap = 1 token per expert
    layer_small = MoE(small)
    assert layer_small.capacity(16) == 1
    y_small, _ = layer_small.apply(variables, x)

    assert float(jnp.linalg.norm(y_small)) < float(jnp.linalg.norm(y_big))


def test_gating_shapes_and_masks():
    t, e, c = 10, 4, 3
    logits = jax.random.normal(jax.random.PRNGKey(2), (t, e))
    dispatch, combine, aux = _top_k_gating(logits, 2, e, c)
    assert dispatch.shape == (t, e, c) and combine.shape == (t, e, c)
    # Each (expert, slot) holds at most one token.
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # Each token dispatched at most top_k times.
    assert float(jnp.max(jnp.sum(dispatch, axis=(1, 2)))) <= 2.0 + 1e-6
    # Combine weights only where dispatched.
    assert float(jnp.max(jnp.abs(combine * (1 - dispatch)))) < 1e-6


def test_moe_expert_parallel_matches_single_device(devices8):
    cfg = MoEConfig(d_model=8, d_ff=16, num_experts=8, top_k=2,
                    capacity_factor=4.0)
    layer = MoE(cfg)
    variables = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8))

    y_ref, _ = layer.apply(variables, x)

    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    params = shard_moe_params(variables["params"], mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    y_ep, _ = jax.jit(
        lambda p, x: layer.apply({"params": p, "state": {}}, x))(params, xs)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)


def test_dryrun_moe_step(devices8):
    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    loss = dryrun_moe_step(mesh, n_experts=8)
    assert np.isfinite(loss)
