"""MoE layer + expert parallelism: routing correctness against a per-token
reference loop, capacity semantics, and ep-sharded execution."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import parallel
from nezha_tpu.parallel.expert import (
    MoE, MoEConfig, dryrun_moe_step, shard_moe_params, _top_k_gating,
)


def _ref_moe(params, x, cfg, capacity):
    """Per-token Python reference: same top-k + capacity-drop semantics."""
    b, s, d = x.shape
    tokens = np.asarray(x, np.float64).reshape(b * s, d)
    rw = np.asarray(params["router"]["w"], np.float64)
    logits = tokens @ rw
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)

    w_in = np.asarray(params["w_in"], np.float64)
    w_out = np.asarray(params["w_out"], np.float64)

    # Assignment order matches _top_k_gating: all top-1 picks first (in token
    # order), then all top-2 picks.
    counts = np.zeros(cfg.num_experts, np.int64)
    y = np.zeros_like(tokens)
    picks = []  # (k, t, e, gate)
    masked = probs.copy()
    for k in range(cfg.top_k):
        idx = masked.argmax(-1)
        for t in range(tokens.shape[0]):
            picks.append((k, t, idx[t], probs[t, idx[t]]))
            masked[t, idx[t]] = -1.0
    for k, t, e, gate in sorted(picks):
        if counts[e] < capacity:
            counts[e] += 1
            h = np.tanh(np.sqrt(2 / np.pi) * (tokens[t] @ w_in[e]) *
                        (1 + 0.044715 * (tokens[t] @ w_in[e]) ** 2))
            gelu = 0.5 * (tokens[t] @ w_in[e]) * (1 + h)
            y[t] += gate * (gelu @ w_out[e])
    return y.reshape(b, s, d)


def test_moe_matches_reference_loop():
    cfg = MoEConfig(d_model=8, d_ff=16, num_experts=4, top_k=2,
                    capacity_factor=8.0)  # capacity large: no drops
    layer = MoE(cfg)
    variables = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 8))
    y, state = layer.apply(variables, x)
    ref = _ref_moe(variables["params"], x, cfg,
                   layer.capacity(x.shape[0] * x.shape[1]))
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-4)
    assert float(state["aux_loss"]) > 0


def test_moe_capacity_drops_tokens():
    """With capacity 1 per expert, most tokens are dropped -> output far
    smaller in norm than with ample capacity."""
    big = MoEConfig(d_model=8, d_ff=16, num_experts=2, top_k=1,
                    capacity_factor=16.0)
    layer_big = MoE(big)
    variables = layer_big.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 8))

    y_big, _ = layer_big.apply(variables, x)

    small = MoEConfig(d_model=8, d_ff=16, num_experts=2, top_k=1,
                      capacity_factor=0.125)  # cap = 1 token per expert
    layer_small = MoE(small)
    assert layer_small.capacity(16) == 1
    y_small, _ = layer_small.apply(variables, x)

    assert float(jnp.linalg.norm(y_small)) < float(jnp.linalg.norm(y_big))


def test_gating_shapes_and_masks():
    t, e, c = 10, 4, 3
    logits = jax.random.normal(jax.random.PRNGKey(2), (t, e))
    dispatch, combine, aux = _top_k_gating(logits, 2, e, c)
    assert dispatch.shape == (t, e, c) and combine.shape == (t, e, c)
    # Each (expert, slot) holds at most one token.
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0 + 1e-6
    # Each token dispatched at most top_k times.
    assert float(jnp.max(jnp.sum(dispatch, axis=(1, 2)))) <= 2.0 + 1e-6
    # Combine weights only where dispatched.
    assert float(jnp.max(jnp.abs(combine * (1 - dispatch)))) < 1e-6


def test_moe_expert_parallel_matches_single_device(devices8):
    cfg = MoEConfig(d_model=8, d_ff=16, num_experts=8, top_k=2,
                    capacity_factor=4.0)
    layer = MoE(cfg)
    variables = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 8))

    y_ref, _ = layer.apply(variables, x)

    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    params = shard_moe_params(variables["params"], mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))
    y_ep, _ = jax.jit(
        lambda p, x: layer.apply({"params": p, "state": {}}, x))(params, xs)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               rtol=1e-4, atol=1e-4)


def test_dryrun_moe_step(devices8):
    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    loss = dryrun_moe_step(mesh, n_experts=8)
    assert np.isfinite(loss)


def test_gpt2_moe_model_trains():
    """MoE as a MODEL, not just a layer (VERDICT r2 weak #5): a GPT-2 with
    routed-expert MLPs trains end-to-end and the aux loss reaches lm_loss."""
    from nezha_tpu import data, optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.train.loop import init_train_state, make_train_step

    model = GPT2(GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                            num_heads=2, hidden_size=32, moe_experts=4))
    # Block 1 (odd) is MoE, block 0 is dense.
    from nezha_tpu.parallel.expert import MoE
    assert isinstance(model.h[1].mlp, MoE)
    assert not isinstance(model.h[0].mlp, MoE)

    opt = optim.adamw(1e-3)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss, donate=False)
    batches = data.synthetic_token_batches(8, seq_len=16, vocab_size=128)
    losses = []
    for _ in range(10):
        state, m = step(state, next(batches))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    # The aux loss is really in the objective: zeroing its weight changes
    # the loss value on identical params/batch.
    model0 = GPT2(GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                             num_heads=2, hidden_size=32, moe_experts=4,
                             moe_aux_weight=0.0))
    variables = model.init(jax.random.PRNGKey(1))
    batch = next(batches)
    out_w, _ = model.apply(variables, batch)
    out_0, _ = model0.apply(variables, batch)
    assert float(lm_loss(out_w, batch)) > float(lm_loss(out_0, batch))


def test_gpt2_moe_ep_sharded_train_step(devices8):
    """The MoE transformer trains under GSPMD with expert weights sharded
    over an ep mesh axis (dp x ep) and matches its own single-device run."""
    from nezha_tpu import data, optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.train.loop import init_train_state, make_train_step
    from jax.sharding import PartitionSpec as P

    cfg = GPT2Config(vocab_size=128, max_positions=32, num_layers=2,
                     num_heads=2, hidden_size=32, moe_experts=4)
    model = GPT2(cfg)
    opt = optim.adamw(1e-3)

    ref_state = init_train_state(model, opt, jax.random.PRNGKey(0))
    ref_step = make_train_step(model, opt, lm_loss, donate=False)

    mesh = parallel.make_mesh({"dp": 2, "ep": 4})
    rules = [
        (r".*/mlp/w_in$", P("ep", None, None)),
        (r".*/mlp/w_out$", P("ep", None, None)),
    ]
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    specs = parallel.param_specs_from_rules(
        state["variables"]["params"], rules)  # unmatched leaves replicate
    state = parallel.shard_train_state(state, mesh, specs)
    step = parallel.make_gspmd_train_step(model, opt, lm_loss, mesh, specs,
                                          donate=False)

    from nezha_tpu.parallel.gspmd import shard_batch_gspmd
    batches = data.synthetic_token_batches(8, seq_len=16, vocab_size=128)
    for _ in range(2):
        b = next(batches)
        ref_state, rm = ref_step(ref_state, b)
        state, m = step(state, shard_batch_gspmd(mesh, b))
        np.testing.assert_allclose(float(m["loss"]), float(rm["loss"]),
                                   rtol=2e-4)
    # Expert weights are physically sharded over ep.
    w_in = state["variables"]["params"]["h1"]["mlp"]["w_in"]
    assert {s.data.shape[0] for s in w_in.addressable_shards} == {1}  # 4/4


def test_gpt2_moe_sequence_parallel_trains(devices8):
    """MoE GPT-2 composes with the dp x sp sequence-parallel train step
    (the default SP loss handles the MoE output dict + aux loss)."""
    from nezha_tpu import data, optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    from nezha_tpu.parallel.sequence_parallel import (make_sp_train_step,
                                                      shard_lm_batch)
    from nezha_tpu.train.loop import init_train_state

    model = GPT2(GPT2Config(vocab_size=128, max_positions=64, num_layers=2,
                            num_heads=4, hidden_size=32, attn_impl="ring",
                            moe_experts=4))
    mesh = parallel.make_mesh({"dp": 2, "sp": 4})
    opt = optim.adamw(1e-3)
    state = parallel.replicate(
        mesh, init_train_state(model, opt, jax.random.PRNGKey(0)))
    step = make_sp_train_step(model, opt, mesh, donate=False)
    batch = shard_lm_batch(
        mesh, next(data.synthetic_token_batches(8, seq_len=32,
                                                vocab_size=128)))
    losses = []
    for _ in range(4):  # same batch: loss must descend
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_gpt2_moe_remat_matches_exact():
    """remat wraps MoE blocks too: the aux-loss state must flow through
    jax.checkpoint unchanged, and gradients must match the non-remat
    model."""
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss

    def build(remat):
        return GPT2(GPT2Config(vocab_size=128, max_positions=32,
                               num_layers=2, num_heads=2, hidden_size=32,
                               moe_experts=4, remat=remat))

    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 17)), jnp.int32)

    def loss_grads(model):
        v = model.init(jax.random.PRNGKey(0))

        def loss(params):
            out, _ = model.apply({"params": params, "state": v["state"]},
                                 {"tokens": tokens}, training=True)
            return lm_loss(out, {"tokens": tokens})  # includes moe aux

        return jax.value_and_grad(loss)(v["params"])

    l0, g0 = loss_grads(build(False))
    l1, g1 = loss_grads(build(True))
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
