"""nezha-pack-images: real images -> NZR1 -> nezha-train e2e (VERDICT r3
missing #5: the repo previously consumed NZR1 but nothing produced it from
actual images)."""

import os

import numpy as np
import pytest

from nezha_tpu.cli.pack_images import build_parser, run
from nezha_tpu.data.images import (
    list_image_folder,
    load_image,
    pack_image_folder,
)


def _write_images(root, classes, per_class, size=(48, 56), fmt="png",
                  seed=0):
    """A tiny ImageFolder tree of real encoded images (PIL round-trip, so
    the pack path exercises actual decode)."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 256, (*size, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"img{i}.{fmt}"))


def _pack(argv):
    return run(build_parser().parse_args(argv))


def test_pack_flat_layout_and_loader_roundtrip(tmp_path):
    """Flat <class>/ layout: stratified split, classes.txt, and the C++
    loader reads the packed records back with matching shape/labels."""
    src, out = tmp_path / "src", tmp_path / "out"
    _write_images(str(src), ["cat", "dog", "emu"], per_class=6)
    summary = _pack([str(src), "--out-dir", str(out), "--size", "32",
                     "--val-fraction", "0.34"])
    assert summary["classes"] == ["cat", "dog", "emu"]
    assert summary["num_train"] + summary["num_val"] == 18
    assert summary["num_val"] == 6  # round(6 * 0.34) = 2 per class
    assert (out / "classes.txt").read_text().split() == ["cat", "dog", "emu"]

    from nezha_tpu.data.native import ImageRecordLoader
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")
    with ImageRecordLoader(str(out / "train.nzr"), batch_size=4,
                           train_augment=False, epochs=1) as loader:
        assert loader.num_examples == summary["num_train"]
        assert loader.shape == (32, 32, 3)
        batch = next(iter(loader))
    assert batch["image"].shape == (4, 32, 32, 3)
    assert set(batch["label"].tolist()) <= {0, 1, 2}
    assert np.all(batch["image"] >= 0) and np.all(batch["image"] <= 1)


def test_pack_train_val_layout_matches_and_determinism(tmp_path):
    """train/+val/ layout packs as-is; identical inputs -> byte-identical
    records (prep must be reproducible); mismatched class lists reject."""
    src = tmp_path / "src"
    _write_images(str(src / "train"), ["a", "b"], per_class=3)
    _write_images(str(src / "val"), ["a", "b"], per_class=2, seed=7)
    s1 = _pack([str(src), "--out-dir", str(tmp_path / "o1"), "--size", "16"])
    s2 = _pack([str(src), "--out-dir", str(tmp_path / "o2"), "--size", "16"])
    assert s1["num_train"] == 6 and s1["num_val"] == 4
    b1 = (tmp_path / "o1" / "train.nzr").read_bytes()
    assert b1 == (tmp_path / "o2" / "train.nzr").read_bytes()

    _write_images(str(src / "val" / "stray"), [], per_class=0)  # extra class
    os.makedirs(src / "val" / "stray", exist_ok=True)
    from PIL import Image
    Image.fromarray(np.zeros((8, 8, 3), np.uint8)).save(
        str(src / "val" / "stray" / "x.png"))
    with pytest.raises(SystemExit, match="class lists differ"):
        _pack([str(src), "--out-dir", str(tmp_path / "o3"), "--size", "16"])


def test_load_image_resize_geometry(tmp_path):
    """Short-side resize + center crop: any aspect ratio lands at
    size x size x 3, grayscale sources are RGB-converted."""
    from PIL import Image

    tall = tmp_path / "tall.png"
    Image.fromarray(np.full((100, 30), 128, np.uint8)).save(str(tall))
    out = load_image(str(tall), 24)
    assert out.shape == (24, 24, 3)


def test_pack_rejects_bad_inputs(tmp_path):
    empty = tmp_path / "empty"
    os.makedirs(empty)
    with pytest.raises(SystemExit, match="no class subdirectories"):
        _pack([str(empty), "--out-dir", str(tmp_path / "o")])
    with pytest.raises(SystemExit, match="val-fraction"):
        _pack([str(empty), "--out-dir", str(tmp_path / "o"),
               "--val-fraction", "1.0"])


def test_pack_rejects_lone_train_dir(tmp_path):
    """src/train/ without src/val/ must reject, not silently pack 'train'
    as the single class with every image labeled 0."""
    src = tmp_path / "src"
    _write_images(str(src / "train"), ["a", "b"], per_class=2)
    with pytest.raises(SystemExit, match="counterpart"):
        _pack([str(src), "--out-dir", str(tmp_path / "o")])


def test_writer_crash_leaves_invalid_file(tmp_path):
    """A pack that dies mid-write must NOT backpatch the record count: the
    truncated file keeps header count 0, which the loader rejects — a
    crashed prep run cannot masquerade as a complete dataset."""
    from nezha_tpu.data.native import ImageRecordWriter
    p = tmp_path / "crash.nzr"
    with pytest.raises(RuntimeError, match="boom"):
        with ImageRecordWriter(str(p), 8, 8, 3) as wr:
            wr.append(np.zeros((8, 8, 3), np.uint8), 0)
            raise RuntimeError("boom")
    header = np.frombuffer(p.read_bytes()[4:20], np.int32)
    assert header[0] == 0  # count never patched

    from nezha_tpu.data.native import ImageRecordLoader, NativeLoaderError
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")
    with pytest.raises(NativeLoaderError):
        ImageRecordLoader(str(p), batch_size=1)


def test_pack_then_train_e2e(devices8, tmp_path):
    """The full story: real PNGs -> nezha-pack-images -> nezha-train
    --data-dir trains AND evals on them (records path, not synthetic)."""
    from nezha_tpu.runtime.native import native_available
    if not native_available():
        pytest.skip("native runtime not available")
    src, out = tmp_path / "src", tmp_path / "data"
    _write_images(str(src), [f"c{i}" for i in range(4)], per_class=8,
                  size=(40, 44))
    summary = _pack([str(src), "--out-dir", str(out), "--size", "36",
                     "--val-fraction", "0.25"])
    assert summary["num_val"] == 8

    from tests.test_cli import _run
    metrics = _run(["--config", "resnet50_imagenet", "--model-preset",
                    "tiny", "--steps", "2", "--batch-size", "8",
                    "--log-every", "1", "--data-dir", str(out),
                    "--crop", "32", "--eval"])
    assert np.isfinite(metrics["loss"])
    assert metrics["eval_count"] == 8  # every packed val record, once
