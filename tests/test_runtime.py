"""Runtime tests: compile cache, executor dispatch, prefetcher."""

import itertools
import time

import jax.numpy as jnp
import numpy as np

from nezha_tpu.graph import Graph
from nezha_tpu.runtime import Executor, Prefetcher, prefetch_to_device


def test_executor_caches_compilations():
    ex = Executor()

    def f(x):
        return x * 2

    a = ex.run(f, jnp.ones((4,)))
    b = ex.run(f, jnp.ones((4,)))
    c = ex.run(f, jnp.ones((8,)))  # new shape -> new compile
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    assert c.shape == (8,)
    stats = ex.stats()
    assert stats["hits"] == 1 and stats["misses"] == 2


def test_executor_runs_graph():
    g = Graph("double")
    x = g.placeholder((4,), name="x")
    g.output(x + x)
    ex = Executor()
    out = ex.run(g, jnp.arange(4.0))
    np.testing.assert_allclose(np.asarray(out), [0.0, 2.0, 4.0, 6.0])
    ex.run(g, jnp.arange(4.0))
    assert ex.stats()["hits"] == 1


def test_prefetcher_yields_all_and_overlaps():
    def slow_source():
        for i in range(10):
            time.sleep(0.01)
            yield {"x": np.full((2,), i, np.float32)}

    got = [int(b["x"][0]) for b in Prefetcher(slow_source(), depth=4)]
    assert got == list(range(10))


def test_prefetcher_multiworker_delivers_all_batches():
    # One worker hitting StopIteration must not truncate batches that other
    # workers are still staging.
    def source():
        for i in range(20):
            yield {"x": np.full((2,), i, np.float32)}

    got = sorted(int(b["x"][0]) for b in Prefetcher(source(), depth=2,
                                                    num_workers=3))
    assert got == list(range(20))


def test_executor_distinguishes_same_shaped_graphs():
    from nezha_tpu.graph import Graph

    g1 = Graph("g")
    x1 = g1.placeholder((4,))
    g1.output(x1 + x1)
    g2 = Graph("g")
    x2 = g2.placeholder((4,))
    g2.output(x2 * x2)
    ex = Executor()
    a = ex.run(g1, jnp.full((4,), 3.0))
    b = ex.run(g2, jnp.full((4,), 3.0))
    np.testing.assert_allclose(np.asarray(a), 6.0)
    np.testing.assert_allclose(np.asarray(b), 9.0)
    assert ex.stats()["misses"] == 2


def test_prefetcher_propagates_errors():
    def bad_source():
        yield {"x": np.zeros(2, np.float32)}
        raise RuntimeError("boom")

    it = prefetch_to_device(bad_source())
    next(it)
    try:
        next(it)
    except RuntimeError as e:
        assert "boom" in str(e)
    else:
        raise AssertionError("error not propagated")


def test_prefetcher_close_mid_stream():
    p = Prefetcher(itertools.count(), depth=2)
    next(p)
    p.close()  # must not hang
