"""Convergence evidence beyond MLP (VERDICT round 1 item 10): GPT-2 trained
on REAL tokens — repo text packed byte-level through the native TokenLoader
— must show decreasing loss. The committed artifact
``artifacts/gpt2_repo_text_loss.jsonl`` is the full-size (124M, real chip)
curve produced by the same pipeline via the CLI; this test runs the tiny-CPU
version end-to-end and also validates the artifact's curve shape."""

import json
import os
from pathlib import Path

import jax
import numpy as np
import pytest

from nezha_tpu.runtime.native import native_available

REPO = Path(__file__).resolve().parent.parent


def test_gpt2_learns_repo_text(tmp_path):
    if not native_available():
        pytest.skip("native runtime not available")
    from nezha_tpu import optim
    from nezha_tpu.data.native import TokenLoader
    from nezha_tpu.data.pack import pack_text_files
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.train.loop import init_train_state, make_train_step

    tok_path = tmp_path / "train.tokens.u16"
    # Stable files only (README/bench churn would shift the data), and a
    # single worker below so batch order is deterministic.
    n = pack_text_files([REPO / "SURVEY.md", REPO / "PAPERS.md"], tok_path)
    assert n > 10000  # real text, not a stub

    model = GPT2(GPT2Config(vocab_size=256, max_positions=64, num_layers=2,
                            num_heads=4, hidden_size=128))
    opt = optim.adamw(3e-3)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss)

    losses = []
    with TokenLoader(tok_path, seq_len=64, batch_size=16, seed=0,
                     num_workers=1) as loader:
        it = iter(loader)
        for _ in range(120):
            state, m = step(state, next(it))
            losses.append(float(m["loss"]))
    first, last = np.mean(losses[:10]), np.mean(losses[-10:])
    assert last < first * 0.8, (first, last)


def test_committed_convergence_artifact_shows_improvement():
    """The committed real-chip GPT-2 124M curve is monotone-ish down."""
    art = REPO / "artifacts" / "gpt2_repo_text_loss.jsonl"
    if not art.exists():
        pytest.skip("artifact not yet recorded")
    rows = [json.loads(l) for l in art.read_text().strip().splitlines()]
    losses = [r["loss"] for r in rows if "loss" in r]
    assert len(losses) >= 5
    # Improvement: final window well below the first loss, and the curve
    # decreases monotone-ish (each third's mean below the previous third's —
    # robust to per-step noise).
    assert np.mean(losses[-3:]) < losses[0] * 0.7
    third = max(len(losses) // 3, 1)
    w1, w2, w3 = (np.mean(losses[:third]), np.mean(losses[third:2 * third]),
                  np.mean(losses[2 * third:]))
    assert w3 < w2 < w1, (w1, w2, w3)
