"""Pipeline parallelism: the SPMD GPipe schedule must be numerically
equivalent to running the same model unpipelined on one device."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu import optim, parallel
from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
from nezha_tpu.parallel import pipeline as pp
from nezha_tpu.train.loop import init_train_state, make_train_step


def _tiny_gpt2(num_layers=4):
    return GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=num_layers,
                           num_heads=2, hidden_size=32))


def _batch(bs=8, seq=9, vocab=64, seed=0):
    toks = np.random.RandomState(seed).randint(0, vocab, (bs, seq))
    return {"tokens": jnp.asarray(toks, jnp.int32)}


def test_pipelined_forward_matches_plain(devices8):
    model = _tiny_gpt2(num_layers=4)
    variables = model.init(jax.random.PRNGKey(0))
    batch = _batch()

    ref_logits, _ = model.apply(variables, batch)

    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    spec = pp.gpt2_pipeline_spec(model)
    outer, blocks = spec.split(variables["params"])
    pparams = {"outer": outer, "blocks": pp.stack_block_params(blocks)}

    out = jax.jit(lambda p: pp.pipelined_forward(
        spec, p, batch, mesh, num_microbatches=2))(pparams)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_roundtrip_params(devices8):
    model = _tiny_gpt2()
    variables = model.init(jax.random.PRNGKey(1))
    spec = pp.gpt2_pipeline_spec(model)
    outer, blocks = spec.split(variables["params"])
    pparams = {"outer": outer, "blocks": pp.stack_block_params(blocks)}
    merged = pp.merge_pipeline_params(spec, pparams)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        variables["params"], merged)


def test_pipeline_train_step_matches_single(devices8):
    model = _tiny_gpt2(num_layers=4)
    opt = optim.adamw(1e-3)
    rng = jax.random.PRNGKey(0)

    # Reference: plain single-device training.
    ref_state = init_train_state(model, opt, rng)
    ref_step = make_train_step(model, opt, lm_loss, donate=False)

    # Pipelined: dp=2 x pp=4.
    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    spec = pp.gpt2_pipeline_spec(model)
    variables = model.init(rng)
    pstate = pp.init_pipeline_state(variables, spec, opt, mesh, rng)
    pstep = pp.make_pipeline_train_step(spec, opt, lm_loss, mesh,
                                        num_microbatches=4, donate=False)

    for i in range(3):
        batch = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, batch)
        pstate, pm = pstep(pstate, batch)
        np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                                   rtol=1e-4, atol=1e-4)

    # Merged pipelined params must match the reference run's params.
    merged = pp.merge_pipeline_params(spec, pstate["pparams"])
    ref_params = ref_state["variables"]["params"]
    keystr = jax.tree_util.keystr
    for (ka, a), (kb, b) in zip(
            sorted(jax.tree_util.tree_leaves_with_path(merged),
                   key=lambda kv: keystr(kv[0])),
            sorted(jax.tree_util.tree_leaves_with_path(ref_params),
                   key=lambda kv: keystr(kv[0]))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=str(ka))


def test_pipeline_fused_head_matches_single(devices8):
    """A fused_loss_chunk model pipelines through the dict-output head_fn
    (the last stage never materializes fp32 [B,S,V]) and matches plain
    single-device fused training."""
    model = GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=4,
                            num_heads=2, hidden_size=32,
                            fused_loss_chunk=-1))
    opt = optim.adamw(1e-3)
    rng = jax.random.PRNGKey(0)

    ref_state = init_train_state(model, opt, rng)
    ref_step = make_train_step(model, opt, lm_loss, donate=False)

    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    spec = pp.gpt2_pipeline_spec(model)
    variables = model.init(rng)
    pstate = pp.init_pipeline_state(variables, spec, opt, mesh, rng)
    pstep = pp.make_pipeline_train_step(spec, opt, lm_loss, mesh,
                                        num_microbatches=4, donate=False)

    for i in range(3):
        batch = _batch(seed=i)
        ref_state, ref_m = ref_step(ref_state, batch)
        pstate, pm = pstep(pstate, batch)
        np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                                   rtol=1e-4, atol=1e-4)


def test_pipeline_dropout_rng_plumbing_is_identity_at_rate_zero(devices8):
    """dropout_rng=True threads keys through embed + every (layer,
    microbatch) application; with rate 0 the masks are identity, so the
    loss must match the deterministic path exactly — proving the rng
    plumbing itself corrupts nothing."""
    model = _tiny_gpt2(num_layers=4)  # dropout=0.0
    opt = optim.adamw(1e-3)
    rng = jax.random.PRNGKey(0)
    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    spec = pp.gpt2_pipeline_spec(model)
    variables = model.init(rng)
    batch = _batch()

    det = pp.make_pipeline_train_step(spec, opt, lm_loss, mesh,
                                      num_microbatches=4, donate=False)
    sto = pp.make_pipeline_train_step(spec, opt, lm_loss, mesh,
                                      num_microbatches=4, donate=False,
                                      dropout_rng=True)
    s0 = pp.init_pipeline_state(variables, spec, opt, mesh, rng)
    s1 = pp.init_pipeline_state(variables, spec, opt, mesh, rng)
    _, m0 = det(s0, batch)
    _, m1 = sto(s1, batch)
    np.testing.assert_allclose(float(m0["loss"]), float(m1["loss"]),
                               rtol=1e-6)


def test_pipeline_trains_with_dropout(devices8):
    """A dropout>0 GPT-2 pipelines with real (per-layer, per-microbatch)
    masks: the stochastic loss differs from the deterministic forward of
    the same params, changes between steps (fresh keys), and training
    stays finite."""
    model = GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=4,
                            num_heads=2, hidden_size=32, dropout=0.5))
    opt = optim.adamw(1e-3)
    rng = jax.random.PRNGKey(0)
    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    spec = pp.gpt2_pipeline_spec(model)
    variables = model.init(rng)
    pstate = pp.init_pipeline_state(variables, spec, opt, mesh, rng)
    pstep = pp.make_pipeline_train_step(spec, opt, lm_loss, mesh,
                                        num_microbatches=4, donate=False,
                                        dropout_rng=True)
    batch = _batch()
    # Deterministic loss of the same initial params for contrast.
    det_logits, _ = model.apply(variables, batch)  # training=False: no drop
    det_loss = float(lm_loss(det_logits, batch))

    losses = []
    for _ in range(3):
        pstate, pm = pstep(pstate, batch)
        losses.append(float(pm["loss"]))
    assert np.isfinite(losses).all()
    # Dropout at 0.5 moves the loss well off the deterministic value and
    # draws fresh masks each step.
    assert abs(losses[0] - det_loss) > 1e-3
    assert losses[0] != losses[1]


def test_pipeline_spec_rejects_moe():
    import pytest
    model = GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=2,
                            num_heads=2, hidden_size=32, moe_experts=4))
    with pytest.raises(ValueError, match="MoE"):
        pp.gpt2_pipeline_spec(model)


def test_pipeline_bubble_independent_of_microbatches(devices8):
    """Loss is identical for any microbatch count (schedule-invariant)."""
    model = _tiny_gpt2(num_layers=2)
    mesh = parallel.make_mesh({"pp": 2})
    spec = pp.gpt2_pipeline_spec(model)
    variables = model.init(jax.random.PRNGKey(2))
    outer, blocks = spec.split(variables["params"])
    pparams = {"outer": outer, "blocks": pp.stack_block_params(blocks)}
    batch = _batch(bs=8)

    outs = [
        jax.jit(lambda p, m=m: pp.pipelined_forward(
            spec, p, batch, mesh, num_microbatches=m))(pparams)
        for m in (1, 2, 4, 8)
    ]
    for o in outs[1:]:
        np.testing.assert_allclose(np.asarray(o), np.asarray(outs[0]),
                                   rtol=2e-4, atol=2e-4)


def test_pipeline_step_refuses_dropout_without_rng():
    """A dropout>0 spec with dropout_rng=False would silently train
    dropless — make_pipeline_train_step must refuse (the guard that
    replaced the old spec-level rejection)."""
    import pytest
    model = GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=4,
                            num_heads=2, hidden_size=32, dropout=0.1))
    spec = pp.gpt2_pipeline_spec(model)
    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    with pytest.raises(ValueError, match="dropout_rng=True"):
        pp.make_pipeline_train_step(spec, optim.adamw(1e-3), lm_loss, mesh,
                                    num_microbatches=2)


def test_pipeline_remat_matches_exact(devices8):
    """Per-tick stage checkpointing changes memory scheduling, not math —
    including with dropout keys, which must replay identically through the
    recompute."""
    model = GPT2(GPT2Config(vocab_size=64, max_positions=16, num_layers=4,
                            num_heads=2, hidden_size=32, dropout=0.3))
    opt = optim.adamw(1e-3)
    rng = jax.random.PRNGKey(0)
    mesh = parallel.make_mesh({"dp": 2, "pp": 4})
    spec = pp.gpt2_pipeline_spec(model)
    variables = model.init(rng)
    batch = _batch()

    losses = {}
    for remat in (False, True):
        state = pp.init_pipeline_state(variables, spec, opt, mesh, rng)
        step = pp.make_pipeline_train_step(spec, opt, lm_loss, mesh,
                                           num_microbatches=4, donate=False,
                                           dropout_rng=True, remat=remat)
        ls = []
        for _ in range(2):
            state, m = step(state, batch)
            ls.append(float(m["loss"]))
        losses[remat] = ls
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)
