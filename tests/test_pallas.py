"""Pallas kernel tests (interpret mode on CPU — same kernel code that
compiles on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import ops
from nezha_tpu.ops.pallas import flash_attention, fused_layer_norm


def _qkv(b=2, h=3, s=64, d=32, seed=0):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, h, s, d)),
            jax.random.normal(kk, (b, h, s, d)),
            jax.random.normal(kv, (b, h, s, d)))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal, None, 16, 16)
    mask = ops.causal_mask(64, 64) if causal else None
    ref = ops.dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_odd_blocks():
    # S not divisible by the requested block -> divisor fallback.
    q, k, v = _qkv(s=48)
    out = flash_attention(q, k, v, True, None, 32, 32)
    ref = ops.dot_product_attention(q, k, v, mask=ops.causal_mask(48, 48))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_grad_matches_reference():
    q, k, v = _qkv(b=1, h=2, s=32, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 16, 16) ** 2)

    def loss_ref(q, k, v):
        out = ops.dot_product_attention(q, k, v, mask=ops.causal_mask(32, 32))
        return jnp.sum(out ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_flash_attention_bf16():
    q, k, v = (t.astype(jnp.bfloat16) for t in _qkv())
    out = flash_attention(q, k, v, True, None, 16, 16)
    assert out.dtype == jnp.bfloat16
    ref = ops.dot_product_attention(q, k, v, mask=ops.causal_mask(64, 64))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), rtol=5e-2,
                               atol=5e-2)


def test_fused_layer_norm_matches_layernorm():
    from nezha_tpu import nn
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 96)) * 3 + 1
    scale = jax.random.normal(jax.random.PRNGKey(1), (96,)) + 1
    bias = jax.random.normal(jax.random.PRNGKey(2), (96,))
    out = fused_layer_norm(x, scale, bias)
    ln = nn.LayerNorm(96)
    ref, _ = ln.apply({"params": {"scale": scale, "bias": bias}, "state": {}}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_bwd_multiblock_noncausal():
    """Fused backward across multiple q AND k blocks, non-causal."""
    rng = np.random.RandomState(3)
    q, k, v = [jnp.asarray(rng.randn(2, 3, 64, 32), jnp.float32)
               for _ in range(3)]

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, False, None, 16, 16) ** 2)

    def loss_ref(q, k, v):
        from nezha_tpu import ops
        return jnp.sum(ops.dot_product_attention(q, k, v) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_bwd_causal_multiblock():
    rng = np.random.RandomState(4)
    q, k, v = [jnp.asarray(rng.randn(1, 2, 96, 16), jnp.float32)
               for _ in range(3)]

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 32, 32) ** 2)

    def loss_ref(q, k, v):
        from nezha_tpu import ops
        mask = ops.causal_mask(96, 96)
        return jnp.sum(ops.dot_product_attention(q, k, v, mask=mask) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_bwd_bf16_grads_match_reference():
    rng = np.random.RandomState(5)
    q, k, v = [jnp.asarray(rng.randn(1, 2, 64, 32), jnp.bfloat16)
               for _ in range(3)]

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, True, None, 32, 32)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        from nezha_tpu import ops
        mask = ops.causal_mask(64, 64)
        out = ops.dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=0.15, rtol=0.1)  # bf16 grain


def test_fused_layer_norm_grads_match_xla():
    """The fused backward kernel's dx/dscale/dbias vs autodiff through the
    composed XLA layer norm."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(6, 128, 64), jnp.float32)
    scale = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(64), jnp.float32)
    w = jnp.asarray(rng.randn(*x.shape), jnp.float32)

    def ref_ln(x, scale, bias):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias

    def loss(fn):
        return lambda x, s, b: jnp.sum(fn(x, s, b) * w)

    g_fused = jax.grad(loss(lambda x, s, b: fused_layer_norm(x, s, b)),
                       argnums=(0, 1, 2))(x, scale, bias)
    g_ref = jax.grad(loss(ref_ln), argnums=(0, 1, 2))(x, scale, bias)
    for a, b, name in zip(g_fused, g_ref, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_fused_layer_norm_bf16_grads_finite():
    x = jnp.asarray(np.random.RandomState(1).randn(4, 64, 32), jnp.bfloat16)
    scale = jnp.ones((32,), jnp.float32)
    bias = jnp.zeros((32,), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(
        fused_layer_norm(x, scale, bias).astype(jnp.float32) ** 2))(x)
    assert np.isfinite(np.asarray(g, np.float32)).all()


def test_fused_layer_norm_mixed_param_dtypes_grad():
    """scale f32 + bias bf16: cotangent dtypes must match each primal."""
    x = jnp.asarray(np.random.RandomState(2).randn(4, 32, 16), jnp.float32)
    scale = jnp.ones((16,), jnp.float32)
    bias = jnp.zeros((16,), jnp.bfloat16)
    g = jax.grad(lambda s, b: jnp.sum(fused_layer_norm(x, s, b)),
                 argnums=(0, 1))(scale, bias)
    assert g[0].dtype == jnp.float32 and g[1].dtype == jnp.bfloat16


def _varlen_setup(s=32, lengths=(20, 32)):
    q, k, v = _qkv(b=len(lengths), h=2, s=s, d=16)
    lens = jnp.asarray(lengths, jnp.int32)
    # Additive mask equivalent to the kernel's right-padding contract:
    # key positions >= length get -inf for every query row.
    kpos = jnp.arange(s)[None, None, None, :]
    mask = jnp.where(kpos < lens[:, None, None, None], 0.0, -jnp.inf)
    # Valid-row selector [B, 1, S, 1] for comparisons/losses: padded QUERY
    # rows are unspecified in the kernel contract.
    valid_q = (jnp.arange(s)[None, :] < lens[:, None])[:, None, :, None]
    return q, k, v, lens, mask, valid_q


def test_flash_varlen_matches_masked_xla():
    """kv_lengths == additive prefix mask on the valid query rows (fwd),
    multi-block so the length boundary crosses block edges."""
    q, k, v, lens, mask, valid_q = _varlen_setup(s=32, lengths=(20, 32))
    out_f = flash_attention(q, k, v, causal=False, block_q=8, block_k=8,
                            kv_lengths=lens)
    out_r = ops.dot_product_attention(q, k, v, mask=mask)
    np.testing.assert_allclose(np.asarray(jnp.where(valid_q, out_f, 0.0)),
                               np.asarray(jnp.where(valid_q, out_r, 0.0)),
                               rtol=5e-4, atol=5e-5)


def test_flash_varlen_grads_match_masked_xla():
    """Gradients through the varlen custom VJP match the masked composed
    path on valid rows; padded keys/values get exactly zero gradient."""
    q, k, v, lens, mask, valid_q = _varlen_setup(s=32, lengths=(20, 32))

    def loss_flash(q, k, v):
        out = flash_attention(q, k, v, causal=False, block_q=8, block_k=8,
                              kv_lengths=lens)
        return jnp.sum(jnp.where(valid_q, out, 0.0) ** 2)

    def loss_ref(q, k, v):
        out = ops.dot_product_attention(q, k, v, mask=mask)
        return jnp.sum(jnp.where(valid_q, out, 0.0) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
    # Padded key/value positions (row 0: length 20 of 32) carry no grad.
    dk, dv = np.asarray(g1[1]), np.asarray(g1[2])
    assert np.all(dk[0, :, 20:, :] == 0.0)
    assert np.all(dv[0, :, 20:, :] == 0.0)
    assert np.any(dk[0, :, :20, :] != 0.0)


def test_flash_varlen_jits_and_batches_lengths():
    """kv_lengths is a traced operand: one compiled program serves
    different length values (no per-batch recompilation)."""
    q, k, v, _, _, _ = _varlen_setup(s=32, lengths=(20, 32))
    f = jax.jit(lambda q, k, v, l: flash_attention(
        q, k, v, causal=False, kv_lengths=l))
    o1 = f(q, k, v, jnp.asarray([20, 32], jnp.int32))
    o2 = f(q, k, v, jnp.asarray([32, 8], jnp.int32))
    assert o1.shape == o2.shape == q.shape
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_flash_varlen_zero_length_clamps_to_one():
    """Length 0 is clamped to 1 (fully-padded row attends to position 0
    only) — finite output, identical to an explicit length-1 call, and no
    silent uniform-attention over padding (ADVICE r4)."""
    q, k, v, _, _, _ = _varlen_setup(s=32, lengths=(20, 32))
    out0 = flash_attention(q, k, v, causal=False, block_q=8, block_k=8,
                           kv_lengths=jnp.asarray([0, 32], jnp.int32))
    out1 = flash_attention(q, k, v, causal=False, block_q=8, block_k=8,
                           kv_lengths=jnp.asarray([1, 32], jnp.int32))
    assert np.all(np.isfinite(np.asarray(out0)))
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out1),
                               rtol=1e-6, atol=1e-7)
    # NOT uniform attention over all positions (the pre-clamp failure
    # mode): row 0 must equal attention restricted to key position 0.
    only_pos0 = jnp.broadcast_to(v[0, :, :1, :], q[0].shape)
    np.testing.assert_allclose(np.asarray(out0[0]), np.asarray(only_pos0),
                               rtol=5e-4, atol=5e-5)
