"""Chunked (fused-head) LM cross-entropy: identical loss and gradients to
the dense [B,S,V]-logits path, without ever materializing that tensor."""

import jax
import jax.numpy as jnp
import numpy as np

from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
from nezha_tpu.ops.losses import (
    chunked_lm_cross_entropy,
    softmax_cross_entropy_with_integer_labels,
)


def _models(chunk=8):
    kw = dict(vocab_size=128, max_positions=64, num_layers=2, num_heads=4,
              hidden_size=32)
    return (GPT2(GPT2Config(**kw)),
            GPT2(GPT2Config(fused_loss_chunk=chunk, **kw)))


def test_chunked_ce_matches_dense():
    rng = np.random.RandomState(0)
    hidden = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)
    emb = jnp.asarray(rng.randn(64, 16), jnp.float32)
    targets = jnp.asarray(rng.randint(0, 64, (2, 32)), jnp.int32)

    dense = softmax_cross_entropy_with_integer_labels(
        jnp.einsum("bsh,vh->bsv", hidden, emb), targets)
    for chunk in (8, 16, 32, 48):  # 48 > S exercises the dense small-path
        fused = chunked_lm_cross_entropy(hidden, emb, targets, chunk=chunk)
        np.testing.assert_allclose(float(fused), float(dense), rtol=1e-6)
    # Ragged chunking of a long sequence must refuse loudly, not silently
    # materialize the dense logits the chunked path exists to avoid.
    import pytest
    with pytest.raises(ValueError, match="not divisible"):
        chunked_lm_cross_entropy(hidden, emb, targets, chunk=5)


def test_dense_bf16_ce_matches_dense():
    """fused_loss_chunk=-1 (logsumexp-fused upcast) == dense CE in fp32."""
    from nezha_tpu.ops.losses import lm_cross_entropy_from_hidden
    rng = np.random.RandomState(0)
    hidden = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)
    emb = jnp.asarray(rng.randn(64, 16), jnp.float32)
    targets = jnp.asarray(rng.randint(0, 64, (2, 32)), jnp.int32)
    dense = softmax_cross_entropy_with_integer_labels(
        jnp.einsum("bsh,vh->bsv", hidden, emb), targets)
    fused = lm_cross_entropy_from_hidden(hidden, emb, targets)
    np.testing.assert_allclose(float(fused), float(dense), rtol=1e-6)


def test_chunked_ce_ignore_index_consistent_across_chunking():
    """-100-masked labels give the same loss whether the scan path or the
    ragged-tail fallback runs (review finding: the two must not diverge)."""
    rng = np.random.RandomState(3)
    hidden = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)
    emb = jnp.asarray(rng.randn(64, 16), jnp.float32)
    t = rng.randint(0, 64, (2, 32))
    t[rng.rand(2, 32) < 0.3] = -100
    t = jnp.asarray(t, jnp.int32)
    losses = [float(chunked_lm_cross_entropy(hidden, emb, t, chunk=c,
                                             ignore_index=-100))
              for c in (8, 32, 48)]  # 48 -> dense small-path
    np.testing.assert_allclose(losses, losses[0] * np.ones(3), rtol=1e-6)


def test_fused_gpt2_loss_and_grads_match_dense():
    for chunk in (8, -1):  # scan-chunked and dense-bf16 fused variants
        dense_model, fused_model = _models(chunk)
        variables = dense_model.init(jax.random.PRNGKey(0))
        batch = {"tokens": jnp.asarray(
            np.random.RandomState(1).randint(0, 128, (2, 33)), jnp.int32)}

        def loss_of(model):
            def f(params):
                out, _ = model.apply({"params": params, "state": {}}, batch)
                return lm_loss(out, batch)
            return jax.jit(jax.value_and_grad(f))(variables["params"])

        dense_loss, dense_grads = loss_of(dense_model)
        fused_loss, fused_grads = loss_of(fused_model)

        np.testing.assert_allclose(float(fused_loss), float(dense_loss),
                                   rtol=1e-5)
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_leaves_with_path(dense_grads),
                jax.tree_util.tree_leaves_with_path(fused_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"chunk={chunk} "
                                       + jax.tree_util.keystr(ka))


def test_fused_bert_mlm_loss_and_grads_match_dense():
    """BertConfig.fused_loss_chunk (-1 dense-bf16, >0 chunked scan) must
    reproduce the fp32-logits MLM loss AND its gradients — including the
    decoder bias and the -100 ignore_index masking neither GPT-2 path
    exercises."""
    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss

    kw = dict(vocab_size=128, max_positions=32, num_layers=2, num_heads=4,
              hidden_size=32)
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, 128, (2, 16)).astype(np.int32)
    labels = np.full_like(tokens, -100)
    mask = rng.rand(2, 16) < 0.3
    labels[mask] = tokens[mask]
    batch = {"tokens": jnp.asarray(tokens), "labels": jnp.asarray(labels),
             "padding_mask": jnp.ones((2, 16), bool)}

    def loss_of(model, variables):
        def f(params):
            out, _ = model.apply({"params": params, "state": {}}, batch,
                                 training=True)
            return mlm_loss(out, batch)
        return jax.jit(jax.value_and_grad(f))(variables["params"])

    dense_model = Bert(BertConfig(**kw))
    variables = dense_model.init(jax.random.PRNGKey(0))
    dense_loss, dense_grads = loss_of(dense_model, variables)

    for chunk in (8, -1):
        fused_model = Bert(BertConfig(fused_loss_chunk=chunk, **kw))
        fused_loss, fused_grads = loss_of(fused_model, variables)
        np.testing.assert_allclose(float(fused_loss), float(dense_loss),
                                   rtol=1e-5)
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_leaves_with_path(dense_grads),
                jax.tree_util.tree_leaves_with_path(fused_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"chunk={chunk} "
                                       + jax.tree_util.keystr(ka))
    # Eval path (training=False) still returns dense logits for accuracy/
    # convert consumers even with the fused config.
    fused_model = Bert(BertConfig(fused_loss_chunk=-1, **kw))
    out, _ = fused_model.apply(variables, batch, training=False)
    assert not isinstance(out, dict) and out.shape == (2, 16, 128)


def test_fused_decode_path_keeps_logits():
    """Generation (cache path) still gets logits even with the fused head."""
    _, fused_model = _models()
    from nezha_tpu.models.generate import init_cache

    variables = fused_model.init(jax.random.PRNGKey(0))
    cache = init_cache(fused_model, batch_size=1, max_len=16)
    tokens = jnp.zeros((1, 4), jnp.int32)
    out, states = fused_model.apply(variables, tokens, cache=cache,
                                    pos=jnp.zeros((), jnp.int32))
    assert not isinstance(out, dict)
    assert out.shape == (1, 4, 128)
