"""nezha-lint: the static-analysis framework (nezha_tpu/analysis/).

Three layers of proof:

1. **fixture mini-packages per rule** — each rule detects its seeded
   violation (positive) and stays quiet on the compliant twin
   (negative); fixtures are PARSED, never imported, so they reference
   jax freely without running it;
2. **baseline round-trip** — findings suppress via line-free keys,
   stale/placeholder entries fail, regeneration preserves
   justifications;
3. **the real tree** — ``nezha-lint`` over this repo exits 0 with the
   committed baseline (THE tier-1 wire: a new host sync, unguarded
   write, post-donation read, unpinned instrument, or registry drift
   fails here), the legacy ``tools/check_*.py`` entry points still
   pass standalone, and the whole lint stays under its 10 s budget.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_ROOT, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from nezha_tpu.analysis import (SourceIndex, apply_baseline,  # noqa: E402
                                load_baseline, load_rules, run_rules,
                                write_baseline)
from nezha_tpu.analysis.baseline import BaselineError  # noqa: E402
from nezha_tpu.cli import lint  # noqa: E402

load_rules()


def _tree(tmp_path, files):
    """Materialize {relpath: source} under tmp_path/pkg and index it."""
    for rel, src in files.items():
        p = tmp_path / "pkg" / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return SourceIndex(str(tmp_path), roots=("pkg",), extra_files=())


def _rule_findings(index, name):
    return [f for f in run_rules(index, [name]) if f.rule == name]


# ------------------------------------------------------ host-sync rule
def test_host_sync_rule_fixture(tmp_path):
    index = _tree(tmp_path, {"hot.py": """
        import jax, time
        import jax.numpy as jnp

        @jax.jit
        def bad_sync(x):
            y = jnp.sum(x)
            y.block_until_ready()          # finding: sync in jit body
            print("trace-time only")       # finding: host IO
            time.sleep(0.1)                # finding: host effect
            return float(y)                # finding: concretize tracer

        @jax.jit
        def good(x, scale=2.0):
            return jnp.sum(x) * float(scale)   # static float(): legal

        def host_side(arr):
            arr.block_until_ready()        # NOT traced: no finding
            return float(arr.sum())
    """})
    found = _rule_findings(index, "host-sync-in-hot-path")
    details = sorted(f.detail for f in found)
    assert details == [".block_until_ready()", "float() on a traced value",
                       "print()", "time.sleep()"]
    assert all(f.symbol == "bad_sync" for f in found)


def test_host_sync_builder_convention_and_scan(tmp_path):
    """The serve-engine idioms: a `_build_*`-returned closure and a
    lax.scan body are both in scope."""
    index = _tree(tmp_path, {"engine.py": """
        import numpy as np
        import jax.numpy as jnp
        from jax import lax

        def _build_step(model):
            def body(carry, _):
                tok = jnp.argmax(carry)
                np.asarray(tok)            # finding: host materialize
                return carry, tok
            def step(carry):
                return lax.scan(body, carry, None, length=4)
            return step

        def _build_prefill(model):
            def prefill(tokens):
                tokens.item()              # finding: concretize
                return tokens
            return prefill
    """})
    found = _rule_findings(index, "host-sync-in-hot-path")
    assert {f.detail for f in found} == {"np.asarray()", ".item()"}
    assert {f.symbol for f in found} == {"_build_step.body",
                                         "_build_prefill.prefill"}


def test_host_sync_host_tier_buffer_fixture(tmp_path):
    """The tiered-KV extension: any touch of the pool's host-tier
    buffers (`_host_tier` and friends) inside a traced body is a
    finding — promotion/demotion are host-side pool maintenance by
    contract — while host-side code uses them freely."""
    index = _tree(tmp_path, {"tier.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad_read(pool, caches, key):
            entry = pool._host_tier[key]          # finding
            used = pool.host_blocks_used          # finding
            return caches, entry, used

        def _build_step(model):
            def step(pool, caches):
                pool._promote(0, [], 0)           # finding
                return caches
            return step

        def host_side(pool):
            pool._host_tier.clear()               # NOT traced: fine
            return pool.host_bytes_resident
    """})
    found = _rule_findings(index, "host-sync-in-hot-path")
    assert {f.detail for f in found} == {"._host_tier",
                                         ".host_blocks_used",
                                         "._promote"}
    assert {f.symbol for f in found} == {"bad_read", "_build_step.step"}


def test_host_sync_pallas_partial_binding(tmp_path):
    """Kernels bound through `kernel = functools.partial(...)` then
    `pallas_call(kernel, ...)` are in scope; a def whose RESULT is
    bound (`mesh = _mesh(devs)`) is not."""
    index = _tree(tmp_path, {"kern.py": """
        import functools
        import jax
        from jax.experimental import pallas as pl

        def _kernel(x_ref, o_ref):
            print("dbg")                   # finding: IO in kernel body

        def call(x, quant):
            kernel = functools.partial(_kernel)
            return pl.pallas_call(kernel, out_shape=None)(x)

        def _mesh(devs):
            print("host-side is fine")     # must NOT be marked traced
            return devs

        def host(devs, f):
            mesh = _mesh(devs)
            return jax.jit(f, device=mesh)
    """})
    found = _rule_findings(index, "host-sync-in-hot-path")
    assert [f.symbol for f in found] == ["_kernel"]


def test_host_sync_collective_ring_bodies(tmp_path):
    """The sequence-sharded prefill extension (ISSUE 20): a function
    that ISSUES lax.ppermute / lax.all_to_all is a traced body even
    when no in-module shard_map references it (the ring-attention
    library helpers are handed to shard_map cross-module), and the
    ring hop loop it builds is in scope transitively — a host sync
    inside a hop is a finding. Collective-free host code stays out of
    scope."""
    index = _tree(tmp_path, {"ring.py": """
        import time
        import jax.numpy as jnp
        from jax import lax

        def ring_attend(q, k, axis):
            def hop(i, carry):
                q_cur, acc = carry
                time.time()                # finding: host clock in hop
                q_cur = lax.ppermute(q_cur, axis, [(0, 1), (1, 0)])
                return q_cur, acc + q_cur
            return lax.fori_loop(0, 2, hop, (q, jnp.zeros_like(q)))

        def ulysses_exchange(x, axis):
            y = lax.all_to_all(x, axis, 1, 2, tiled=True)
            print("trace-time only")       # finding: IO in a2a body
            return y

        def host_plan(widths):
            print("host-side is fine")     # no collectives: NOT traced
            return sorted(widths)
    """})
    found = _rule_findings(index, "host-sync-in-hot-path")
    assert {f.detail for f in found} == {"time.time()", "print()"}
    assert {f.symbol for f in found} == {"ring_attend.hop",
                                         "ulysses_exchange"}


# -------------------------------------------- mesh-host-side-tables rule
def test_mesh_host_side_tables_rule_fixture(tmp_path):
    """The sharded-serving split: host-side pool bookkeeping
    (block tables / free list / trie) must never mutate inside a
    shard_map-lowered body — including transitively-called helpers —
    while reads of an uploaded copy, host-side mutation, and mutation
    inside a PLAIN jit body stay legal."""
    index = _tree(tmp_path, {"sharded.py": """
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map

        class Pool:
            def bind(self, slot):                # host-side: legal
                self.tables_host[slot, 0] = 1
                self._free_blocks.pop()

        def _helper(pool, b):
            pool._refs[b] += 1                   # finding (transitive)

        def run(pool, mesh, caches, tables):
            def body(c, t):
                pool.tables_host[0, 0] = 9       # finding: table write
                pool._free_blocks.append(3)      # finding: mutator call
                pool.trie.insert([1], [2], None) # finding: trie mutate
                _helper(pool, 0)
                row = t[0]                       # READ of upload: legal
                return c + row
            f = shard_map(body, mesh=mesh, in_specs=None,
                          out_specs=None)
            return f(caches, tables)
    """})
    found = _rule_findings(index, "mesh-host-side-tables")
    assert {f.detail for f in found} == {"tables_host", "_free_blocks",
                                         "trie", "_refs"}
    assert {f.symbol for f in found} == {"run.body", "_helper"}
    # Negative twin: the same mutations outside any shard_map body.
    clean = _tree(tmp_path / "neg", {"host.py": """
        import jax

        class Pool:
            def free(self, slot):
                self.tables_host[slot, :] = 0
                self._free_blocks.append(slot)

        @jax.jit
        def step(caches, tables):
            return caches                        # jit body, no mutation
    """})
    assert _rule_findings(clean, "mesh-host-side-tables") == []


def test_mesh_host_side_tables_collective_bodies(tmp_path):
    """The sequence-sharded prefill extension (ISSUE 20): a helper
    that issues mesh collectives (the seq_prefill ring/ulysses shard
    bodies — handed to shard_map cross-module, so no in-module
    shard_map call roots them) is still in scope: a block-table or
    free-list mutation inside one is a finding."""
    index = _tree(tmp_path, {"seq.py": """
        import jax.numpy as jnp
        from jax import lax

        def _ring_shard(pool, q, kd, axis):
            pool._free_blocks.append(3)    # finding: fork per shard
            def hop(i, carry):
                return lax.ppermute(carry, axis, [(0, 1), (1, 0)])
            return lax.fori_loop(0, 2, hop, kd)

        def _ulysses_shard(pool, q, tab, axis):
            qh = lax.all_to_all(q, axis, 1, 2, tiled=True)
            pool.tables_host[0, 0] = 9     # finding: table write
            return qh + tab

        def host_rebind(pool, slot):
            pool.tables_host[slot, :] = 0  # host-side: legal
            pool._free_blocks.append(slot)
    """})
    found = _rule_findings(index, "mesh-host-side-tables")
    assert {f.detail for f in found} == {"_free_blocks", "tables_host"}
    assert {f.symbol for f in found} == {"_ring_shard", "_ulysses_shard"}


def test_mesh_host_side_tables_real_tree_clean():
    """The real serving tree honors the split: the engine's shard_map
    surfaces (nested flash kernels, the sharded engine's programs)
    never touch the host bookkeeping."""
    index = SourceIndex(_ROOT, roots=("nezha_tpu",), extra_files=())
    assert _rule_findings(index, "mesh-host-side-tables") == []


# -------------------------------------------------- traced-branch rule
def test_traced_branch_rule_fixture(tmp_path):
    index = _tree(tmp_path, {"branchy.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def bad(x):
            y = jnp.sum(x)
            if y > 0:                      # finding
                x = x + 1
            while jnp.any(x):              # finding (device call test)
                x = x - 1
            assert y != 0                  # finding
            return x

        @jax.jit
        def good(x, flag=True, k=None):
            y = jnp.sum(x)
            if flag:                       # static closure value: legal
                x = x + 1
            if k is None:                  # identity test: legal
                x = x * 2
            if jnp.issubdtype(x.dtype, jnp.floating):   # static: legal
                x = x + 0.0
            assert x.shape[0] == 1         # shape is static: legal
            return x + y
    """})
    found = _rule_findings(index, "traced-value-branch")
    assert sorted(f.detail for f in found) == [
        "assert y != 0", "if y > 0", "while jnp.any(x)"]
    assert all(f.symbol == "bad" for f in found)


# ------------------------------------------------------- donation rule
def test_donation_rule_fixture(tmp_path):
    index = _tree(tmp_path, {"donate.py": """
        import jax

        def update(state, x):
            return state

        step = jax.jit(update, donate_argnums=(0,))

        def bad_loop(state, xs):
            out = step(state, xs)
            return state                   # finding: donated, then read

        def good_loop(state, xs):
            state = step(state, xs)        # rebound in-statement: legal
            return state

        class Engine:
            def __init__(self):
                from runtime import Executor
                self.executor = Executor(donate_argnums=(1,))

            def bad_step(self):
                out = self.executor.run(self.fn, self.variables,
                                        self.pool.caches)
                return self.pool.caches    # finding: read after donate

            def good_step(self):
                out = self.executor.run(self.fn, self.variables,
                                        self.pool.caches)
                self.pool.caches = out[0]  # rebind revives the path
                return self.pool.caches

            def branched(self, paged):
                if paged:
                    out = self.executor.run(self.fn, self.variables,
                                            self.pool.caches)
                else:
                    out = self.fallback(self.pool.caches)   # sibling arm: legal
                self.pool.caches = out[0]
                return out
    """})
    found = _rule_findings(index, "use-after-donate")
    assert sorted((f.symbol, f.detail) for f in found) == [
        ("Engine.bad_step", "self.pool.caches"),
        ("bad_loop", "state"),
    ]


# ---------------------------------------------------------- locks rule
def test_lock_discipline_rule_fixture(tmp_path):
    index = _tree(tmp_path, {"locked.py": """
        import threading

        class Pool:
            _LOCK_GUARDED = {"_free": "_lock", "_ledger": "_ledger_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self._ledger_lock = threading.Lock()
                self._free = []            # __init__ is exempt
                self._ledger = {}

            def good(self, x):
                with self._lock:
                    self._free.append(x)
                with self._ledger_lock:
                    self._ledger[x] = 1

            def bad(self, x):
                self._free.append(x)       # finding: no lock
                with self._lock:
                    self._ledger[x] = 1    # finding: WRONG lock held

            def internal(self, x):
                '''[holds: _lock] — caller locks.'''
                self._free.pop()           # marker: legal
                del self._free[0]          # marker: legal

            def nested_ok(self, xs):
                for x in xs:
                    with self._lock:
                        self._free.append(x)   # nested with: legal

        class Undeclared:
            def anything(self, x):
                self._free.append(x)       # no declaration: not checked
    """})
    found = _rule_findings(index, "lock-discipline")
    assert sorted((f.symbol, f.detail) for f in found) == [
        ("Pool.bad", "_free"), ("Pool.bad", "_ledger")]


def test_lock_discipline_real_declarations_present():
    """The serve/obs classes actually declare their guarded state — the
    rule has teeth on the real tree, not just fixtures."""
    from nezha_tpu.obs.registry import Histogram, Registry
    from nezha_tpu.serve.router import Router
    from nezha_tpu.serve.scheduler import Scheduler
    from nezha_tpu.serve.supervisor import Supervisor, _ThreadWorker
    for cls in (Scheduler, Router, Supervisor, _ThreadWorker,
                Histogram, Registry):
        assert getattr(cls, "_LOCK_GUARDED"), cls.__name__
    assert Scheduler._LOCK_GUARDED["_lanes"] == "_lock"
    assert Scheduler._LOCK_GUARDED["_preempted"] == "_lock"
    assert Supervisor._LOCK_GUARDED["_as_target"] == "_lock"
    assert Router._LOCK_GUARDED["retries"] == "_ledger_lock"


# ------------------------------------------------- registry-port rules
def test_fault_points_rule_fixture(tmp_path):
    from nezha_tpu.analysis.rules.fault_points import check_index
    for rel, src in {
        "nezha_tpu/a.py": """
            from nezha_tpu import faults

            def f():
                faults.point("serve.test")

            def g():
                faults.point("serve.undocumented")
        """,
        "nezha_tpu/b.py": """
            from nezha_tpu import faults

            def h():
                faults.point("serve.test")   # duplicate site
        """,
        "nezha_tpu/faults/injector.py": """
            # Excluded dir: examples here never register.
            def point(name):
                'call like faults.point("serve.fake")'
        """,
        "docs/RUNBOOK.md": "| serve.test | documented |\n",
        "tests/test_x.py": "PLAN = 'serve.test:error'\n",
    }.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    index = SourceIndex(str(tmp_path), roots=("nezha_tpu",),
                        extra_files=())
    msgs = [f.message for f in check_index(
        index, expected=frozenset({"serve.test", "serve.missing"}))]
    assert any("2 call sites" in m for m in msgs)            # duplicate
    assert any("'serve.undocumented' is not in EXPECTED" in m
               for m in msgs)
    assert any("'serve.missing' has no" in m for m in msgs)  # lost pin
    assert any("'serve.undocumented' is not documented" in m
               for m in msgs)
    assert any("'serve.undocumented' is not covered" in m for m in msgs)
    # The documented+tested+pinned point raises nothing about itself.
    assert not any("'serve.test' is not" in m for m in msgs)


def test_telemetry_schema_rule_fixture(tmp_path):
    index = _tree(tmp_path, {"instrumented.py": """
        from nezha_tpu import obs

        def ok():
            obs.counter("serve.admitted_total").inc()
            obs.histogram("router.route_s").observe(0.1)
            obs.counter("compile_cache.hits").inc()   # unpinned ns: free
            obs.counter(f"serve.dynamic_total").inc() # non-literal: skip

        def bad():
            obs.counter("serve.bogus_total").inc()    # unknown name
            obs.counter("serve.ttft_s").inc()         # kind mismatch
            with obs.span("serve.mystery"):           # unpinned span
                pass
    """})
    found = _rule_findings(index, "telemetry-schema")
    assert sorted(f.detail for f in found) == [
        "serve.bogus_total", "serve.mystery", "serve.ttft_s"]
    kind_mismatch = [f for f in found if f.detail == "serve.ttft_s"]
    assert "histogram" in kind_mismatch[0].message


def test_bench_records_rule_fixture(tmp_path):
    (tmp_path / "nezha_tpu").mkdir()
    (tmp_path / "BENCH_crash.json").write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 1, "tail": "boom", "parsed": None}))
    (tmp_path / "BENCH_ok.json").write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0, "tail": "",
         "parsed": {"metric": "m", "value": 1.0}, "platform": "cpu"}))
    index = SourceIndex(str(tmp_path), roots=("nezha_tpu",),
                        extra_files=())
    found = _rule_findings(index, "bench-records")
    assert len(found) == 1 and "CRASH RECORD" in found[0].message
    assert found[0].file == "BENCH_crash.json"
    # Superseding the crash clears the finding.
    (tmp_path / "BENCH_NOTES.md").write_text(
        "## Superseded records\n- BENCH_crash.json — crash\n")
    assert _rule_findings(index, "bench-records") == []


# ------------------------------------------------------------ baseline
def test_baseline_round_trip(tmp_path):
    index = _tree(tmp_path, {"hot.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.sum(x)
            y.block_until_ready()
            return y
    """})
    findings = run_rules(index)
    assert findings
    path = tmp_path / "baseline.json"
    write_baseline(findings, str(path),
                   default_justification="fixture: accepted on purpose")
    baseline = load_baseline(str(path))
    kept, stale = apply_baseline(findings, baseline)
    assert kept == [] and stale == []
    # Keys are line-free: shifting the violation down a line still
    # suppresses; deleting it makes the entry STALE.
    # (run_rules ran EVERY rule — the registry rules report the bare
    # fixture tree's missing artifacts too, and those baseline the
    # same way.)
    assert any(k.startswith("host-sync-in-hot-path:pkg/hot.py:f:")
               for k in baseline)
    kept, stale = apply_baseline([], baseline)
    assert stale == sorted(baseline)


def test_baseline_rejects_placeholder_and_garbage(tmp_path):
    path = tmp_path / "b.json"
    path.write_text(json.dumps({"version": 1, "suppressions": [
        {"key": "x:y:z:w", "justification": "TODO: justify"}]}))
    with pytest.raises(BaselineError):
        load_baseline(str(path))
    path.write_text("{torn")
    with pytest.raises(BaselineError):
        load_baseline(str(path))
    path.write_text(json.dumps({"version": 99, "suppressions": []}))
    with pytest.raises(BaselineError):
        load_baseline(str(path))
    # Missing file = empty baseline, not an error.
    assert load_baseline(str(tmp_path / "absent.json")) == {}


def test_update_baseline_preserves_justifications(tmp_path, capsys):
    """Regeneration keeps human-written reasons — even when the file
    currently holds a placeholder entry a strict load rejects — and
    refuses both partial (--rule) rewrites and unreadable files."""
    from nezha_tpu.analysis.baseline import PLACEHOLDER_JUSTIFICATION
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "hot.py").write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            y = jnp.sum(x)
            y.block_until_ready()
            return y
    """))
    index = SourceIndex(str(root), roots=("pkg",), extra_files=())
    [finding] = _rule_findings(index, "host-sync-in-hot-path")
    path = tmp_path / "baseline.json"
    # A human-justified entry for the real finding + a placeholder one
    # (the state a previous --update-baseline leaves behind).
    path.write_text(json.dumps({"version": 1, "suppressions": [
        {"key": finding.key, "justification": "reviewed: intentional"},
        {"key": "other-rule:gone.py::x",
         "justification": PLACEHOLDER_JUSTIFICATION}]}))
    existing = load_baseline(str(path), strict=False)
    write_baseline([finding], str(path), justifications=existing)
    saved = json.loads(path.read_text())
    [entry] = saved["suppressions"]
    assert entry["key"] == finding.key
    assert entry["justification"] == "reviewed: intentional"
    # A NEW finding regenerated without a human reason gets the
    # placeholder, and the placeholder fails the next strict load.
    write_baseline([finding], str(path), justifications={})
    with pytest.raises(BaselineError):
        load_baseline(str(path))
    # --update-baseline + --rule would delete other rules' entries.
    assert lint.main(["--root", str(root), "--rule", "bench-records",
                      "--update-baseline",
                      "--baseline", str(path)]) == 2
    # Structural damage aborts the rewrite instead of wiping the file.
    path.write_text("{torn")
    assert lint.main(["--root", str(root), "--update-baseline",
                      "--baseline", str(path)]) == 2
    assert path.read_text() == "{torn"


def test_shims_run_without_jax(tmp_path):
    """The standalone checkers keep their original no-dependencies
    promise: with jax import-blocked they fall back to the namespace
    stub and still validate the real tree."""
    blocker = tmp_path / "runner.py"
    blocker.write_text(textwrap.dedent("""
        import sys
        class _Block:
            def find_module(self, name, path=None):
                if name.split(".")[0] in ("jax", "jaxlib"):
                    return self
                return None
            def load_module(self, name):
                raise ImportError(f"{name} blocked (simulated)")
        sys.meta_path.insert(0, _Block())
        import runpy
        sys.argv = sys.argv[1:]
        runpy.run_path(sys.argv[0], run_name="__main__")
    """))
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    for tool in ("check_fault_points.py", "check_bench_record.py"):
        p = subprocess.run(
            [sys.executable, str(blocker),
             os.path.join(_ROOT, "tools", tool)],
            capture_output=True, text=True, env=env, cwd="/")
        assert p.returncode == 0, (tool, p.stdout, p.stderr)
        assert p.stdout.startswith("OK:"), (tool, p.stdout)


def test_stale_baseline_fails_cli(tmp_path):
    index_dir = tmp_path / "repo"
    (index_dir / "pkg").mkdir(parents=True)
    (index_dir / "pkg" / "clean.py").write_text("x = 1\n")
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps({"version": 1, "suppressions": [
        {"key": "host-sync-in-hot-path:gone.py:f:.item()",
         "justification": "the code this excused was deleted"}]}))
    rc = lint.main(["--root", str(index_dir), "--baseline", str(stale)])
    assert rc == 1


# ----------------------------------------------------------------- CLI
def test_cli_json_and_rule_selection(tmp_path, capsys):
    (tmp_path / "nezha_tpu").mkdir()
    (tmp_path / "nezha_tpu" / "m.py").write_text(textwrap.dedent("""
        import threading

        class C:
            _LOCK_GUARDED = {"_state": "_lock"}

            def __init__(self):
                self._lock = threading.Lock()
                self._state = []

            def bad(self):
                self._state.append(1)
    """))
    rc = lint.main(["--root", str(tmp_path), "--json", "--no-baseline",
                    "--rule", "lock-discipline"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["rules"] == ["lock-discipline"]
    [f] = out["findings"]
    assert f["rule"] == "lock-discipline" and f["detail"] == "_state"
    assert f["key"].startswith("lock-discipline:nezha_tpu/m.py:C.bad:")
    # Selecting only another rule ignores the violation.
    rc = lint.main(["--root", str(tmp_path), "--no-baseline",
                    "--rule", "bench-records", "--rule",
                    "fault-points"])
    assert rc == 1   # fault-points: no sites found in this tiny tree
    rc = lint.main(["--root", str(tmp_path), "--no-baseline",
                    "--rule", "use-after-donate"])
    assert rc == 0


def test_single_rule_run_ignores_other_rules_suppressions():
    """`nezha-lint --rule X` on the clean tree must NOT report the
    committed baseline's other-rule entries as stale (a single-rule
    run only produces X's findings, so only X's suppressions can be
    judged) — the RUNBOOK §11 invocation exits 0."""
    assert lint.main(["--root", _ROOT, "--rule", "lock-discipline"]) == 0
    assert lint.main(["--root", _ROOT, "--rule",
                      "traced-value-branch"]) == 0


def test_cli_unknown_rule_and_list(capsys):
    assert lint.main(["--rule", "no-such-rule",
                      "--root", _ROOT]) == 2
    assert lint.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for name in ("host-sync-in-hot-path", "traced-value-branch",
                 "use-after-donate", "lock-discipline", "fault-points",
                 "telemetry-schema", "bench-records"):
        assert name in out


def test_syntax_error_is_a_finding(tmp_path):
    (tmp_path / "nezha_tpu").mkdir()
    (tmp_path / "nezha_tpu" / "broken.py").write_text("def f(:\n")
    rc = lint.main(["--root", str(tmp_path), "--no-baseline",
                    "--rule", "use-after-donate"])
    assert rc == 1   # parse failures surface regardless of selection


# ---------------------------------------------------- the real tree
def test_nezha_lint_real_tree_exits_zero_under_budget():
    """THE tier-1 wire: all rules over the real repo, committed
    baseline applied, exit 0 — and within the 10 s CPU budget the
    RUNBOOK promises (index once, parse once)."""
    t0 = time.monotonic()
    rc = lint.main(["--root", _ROOT])
    dt = time.monotonic() - t0
    assert rc == 0
    assert dt < 10.0, f"nezha-lint took {dt:.1f}s (budget 10s)"


def test_real_tree_runs_all_seven_rules(capsys):
    rc = lint.main(["--root", _ROOT, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert len(out["rules"]) >= 7
    assert out["files_indexed"] > 100
    # The committed baseline suppresses only justified findings; every
    # justification is real (load_baseline rejects placeholders).
    baseline = load_baseline(os.path.join(_ROOT, "tools",
                                          "lint_baseline.json"))
    assert out["suppressed"] == len(baseline)


def test_legacy_shims_standalone():
    """The three tools/check_*.py entry points survive the migration:
    same argv contract, same rc, now over the shared analysis index."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)   # shims must bootstrap sys.path alone
    for tool in ("check_fault_points.py", "check_bench_record.py"):
        p = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools", tool)],
            capture_output=True, text=True, env=env, cwd="/")
        assert p.returncode == 0, (tool, p.stdout, p.stderr)
        assert p.stdout.startswith("OK:"), (tool, p.stdout)
    p = subprocess.run(
        [sys.executable,
         os.path.join(_ROOT, "tools", "check_telemetry_schema.py")],
        capture_output=True, text=True, env=env, cwd="/")
    assert p.returncode == 2    # usage: needs a run dir
    # And a bad run dir still fails through the shim import path.
    from check_telemetry_schema import check_run_dir
    assert check_run_dir("/nonexistent-run-dir") != []
