"""Decoding tests: KV-cache generation must match full-forward decoding
exactly (greedy), sampling shapes/determinism, and cache bounds."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu.models.generate import generate, init_cache
from nezha_tpu.models.gpt2 import GPT2, GPT2Config

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    variables = model.init(jax.random.PRNGKey(0))
    return model, variables


def _naive_greedy(model, variables, prompt, n):
    """Reference decode: full forward each step, at a FIXED padded length
    so jit compiles once instead of once per prefix length (causality
    makes the tail padding invisible to the positions we read)."""
    b, p = prompt.shape
    toks = jnp.zeros((b, p + n), jnp.int32).at[:, :p].set(
        jnp.asarray(prompt, jnp.int32))
    fwd = jax.jit(lambda v, t: model.apply(v, t, training=False)[0])
    for i in range(n):
        logits = fwd(variables, toks)
        nxt = jnp.argmax(logits[:, p + i - 1, :], axis=-1).astype(jnp.int32)
        toks = toks.at[:, p + i].set(nxt)
    return toks


def test_cached_greedy_matches_full_forward(model_and_vars):
    model, variables = model_and_vars
    prompt = np.array([[5, 17, 3, 42], [7, 7, 23, 1]], np.int32)
    fast = generate(model, variables, prompt, max_new_tokens=12,
                    temperature=0.0, cache_dtype=jnp.float32)
    slow = _naive_greedy(model, variables, prompt, 12)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_prefill_logits_match_plain_forward(model_and_vars):
    """The cached prefill pass itself must reproduce the plain forward."""
    model, variables = model_and_vars
    prompt = jnp.asarray([[5, 17, 3, 42, 8, 30]], jnp.int32)
    plain, _ = model.apply(variables, prompt, training=False)
    cache = init_cache(model, 1, 16, jnp.float32)
    cached, _ = model.apply(variables, prompt, training=False,
                            cache=cache, pos=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(plain), np.asarray(cached),
                               atol=1e-5, rtol=1e-5)


def test_sampling_is_rng_deterministic(model_and_vars):
    model, variables = model_and_vars
    prompt = np.array([[1, 2, 3]], np.int32)
    a = generate(model, variables, prompt, 8, temperature=0.8, top_k=10,
                 rng=jax.random.PRNGKey(7))
    b = generate(model, variables, prompt, 8, temperature=0.8, top_k=10,
                 rng=jax.random.PRNGKey(7))
    c = generate(model, variables, prompt, 8, temperature=0.8, top_k=10,
                 rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert a.shape == (1, 11)
    assert int(a.max()) < CFG["vocab_size"] and int(a.min()) >= 0


def test_generate_respects_max_positions(model_and_vars):
    model, variables = model_and_vars
    prompt = np.zeros((1, 60), np.int32)
    with pytest.raises(ValueError, match="max_positions"):
        generate(model, variables, prompt, max_new_tokens=10)


def test_top_p_nucleus_filtering():
    """Sampled ids stay inside the nucleus; tiny top_p degrades to argmax
    (the first token always survives the exclusive-cumsum mask)."""
    from nezha_tpu.models.generate import _sample
    # probs ~ [0.62, 0.23, 0.084, 0.031, ...]: nucleus(0.5) = {0}
    logits = jnp.asarray([[5.0, 4.0, 3.0, 2.0, 1.0]], jnp.float32)
    for i in range(20):
        tok = _sample(logits, jax.random.PRNGKey(i), 1.0, None, 0.5)
        assert int(tok[0]) == 0
    # nucleus(0.9) = {0, 1, 2}; over many draws nothing outside appears
    seen = {int(_sample(logits, jax.random.PRNGKey(i), 1.0, None, 0.9)[0])
            for i in range(200)}
    assert seen <= {0, 1, 2} and len(seen) > 1
    # top_p=1.0 is a no-op: identical draw to the unfiltered path
    for i in range(5):
        a = _sample(logits, jax.random.PRNGKey(i), 1.0, None, 1.0)
        b = _sample(logits, jax.random.PRNGKey(i), 1.0, None, None)
        assert int(a[0]) == int(b[0])
    # top_p <= 0 degrades to argmax — never to an empty nucleus (which
    # categorical would silently turn into always-id-0). Max logit is at
    # index 0 here, so assert via a shifted copy whose argmax is index 3.
    shifted = jnp.asarray([[1.0, 2.0, 3.0, 5.0, 4.0]], jnp.float32)
    for p in (0.0, -1.0):
        for i in range(10):
            tok = _sample(shifted, jax.random.PRNGKey(i), 1.0, None, p)
            assert int(tok[0]) == 3


def test_generate_with_top_p(model_and_vars):
    model, variables = model_and_vars
    prompt = jnp.zeros((1, 4), jnp.int32)
    out = generate(model, variables, prompt, max_new_tokens=6,
                   temperature=0.8, top_k=None, top_p=0.9,
                   rng=jax.random.PRNGKey(0))
    assert out.shape == (1, 10)
    out2 = generate(model, variables, prompt, max_new_tokens=6,
                    temperature=0.8, top_k=None, top_p=0.9,
                    rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_generate_flash_prefill_matches_composed():
    """Prefill through the causal flash kernel (attn_impl='flash' forces
    it, interpret mode on CPU) produces the same greedy tokens as the
    composed cache-masked path — nothing precedes the prompt, so causal
    flash over the chunk is exact."""
    from nezha_tpu.models.generate import generate
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    kw = dict(vocab_size=128, max_positions=32, num_layers=2,
              num_heads=2, hidden_size=32)
    m_flash = GPT2(GPT2Config(attn_impl="flash", **kw))
    m_xla = GPT2(GPT2Config(attn_impl="xla", **kw))
    v = m_xla.init(jax.random.PRNGKey(0))
    # cache_dtype f32 (as the exactness test above): the xla path reads
    # K/V through the cache, flash reads them raw — bf16 cache rounding
    # would make exact-token equality seed-fragile.
    prompt = np.asarray([[5, 9, 2, 11, 7, 3, 1, 8]], np.int32)
    a = generate(m_flash, v, prompt, max_new_tokens=6, temperature=0.0,
                 cache_dtype=jnp.float32)
    b = generate(m_xla, v, prompt, max_new_tokens=6, temperature=0.0,
                 cache_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # Non-multiple-of-128 prompt exercises the padded+kv_lengths path
    # (here length 8 already does: pad to 128); a longer odd length too.
    prompt = np.asarray([[3] * 13], np.int32)
    a = generate(m_flash, v, prompt, max_new_tokens=4, temperature=0.0,
                 cache_dtype=jnp.float32)
    b = generate(m_xla, v, prompt, max_new_tokens=4, temperature=0.0,
                 cache_dtype=jnp.float32)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_prefill_nonzero_pos_falls_back_to_masked(model_and_vars):
    """The flash-prefill contract (ADVICE r5): ``prefill=True`` with a
    cache position that is not statically zero must NOT take the
    chunk-local flash path (it would drop attention to the cached
    prefix). A forced-flash model fed prefill=True at pos=4 must match
    the plain masked-cache path exactly."""
    kw = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
              hidden_size=64)
    m_flash = GPT2(GPT2Config(attn_impl="flash", **kw))
    m_xla = GPT2(GPT2Config(attn_impl="xla", **kw))
    variables = m_xla.init(jax.random.PRNGKey(1))
    prefix = jnp.asarray([[5, 17, 3, 42]], jnp.int32)
    chunk = jnp.asarray([[8, 30, 2, 9]], jnp.int32)
    from nezha_tpu.models.generate import _caches_from_states

    cache = init_cache(m_xla, 1, 16, jnp.float32)
    _, st = m_xla.apply(variables, prefix, training=False, cache=cache,
                        pos=0)
    warm = _caches_from_states(m_xla, st, cache)
    # Reference: continue WITHOUT the prefill hint (masked path).
    ref, _ = m_xla.apply(variables, chunk, training=False,
                         cache=warm, pos=4)
    # prefill=True at pos=4: the guard must fall back, not mis-attend.
    out, _ = m_flash.apply(variables, chunk, training=False,
                           cache=warm, pos=4, prefill=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_generate_eos_early_stop(model_and_vars):
    """Rows that emit eos_id keep decoding (static shapes) but their
    later tokens are masked to the pad (default: eos itself); other rows
    are bit-identical to the no-eos run."""
    model, variables = model_and_vars
    prompt = np.array([[5, 17, 3, 42], [7, 7, 23, 1]], np.int32)
    kw = dict(max_new_tokens=10, temperature=0.8, top_k=20,
              cache_dtype=jnp.float32, rng=jax.random.PRNGKey(5))
    base = np.asarray(generate(model, variables, prompt, **kw))[:, 4:]
    # Plant row 0's first non-repeated token as EOS; row 1 untouched.
    row = base[0].tolist()
    stop = next(i for i in range(1, len(row)) if row[i] not in row[:i])
    eos = row[stop]
    out = np.asarray(generate(model, variables, prompt, **kw,
                              eos_id=eos))[:, 4:]
    assert out[0, :stop + 1].tolist() == row[:stop + 1]
    assert all(t == eos for t in out[0, stop:].tolist())
    np.testing.assert_array_equal(out[1], base[1])
    # Explicit pad_id: tail pads with it instead of eos.
    out2 = np.asarray(generate(model, variables, prompt, **kw,
                               eos_id=eos, pad_id=0))[:, 4:]
    assert out2[0, stop] == eos
    assert all(t == 0 for t in out2[0, stop + 1:].tolist())


def test_sample_top_k_clamped():
    """_sample no longer reaches lax.top_k with k outside [1, vocab]."""
    from nezha_tpu.models.generate import _sample
    logits = jnp.asarray([[5.0, 4.0, 3.0, 2.0, 1.0]], jnp.float32)
    for bad_k in (0, -3, 99):
        tok = _sample(logits, jax.random.PRNGKey(0), 1.0, bad_k, None)
        assert 0 <= int(tok[0]) < 5
    # k<=0 clamps to 1 == argmax regardless of rng
    for i in range(10):
        assert int(_sample(logits, jax.random.PRNGKey(i), 1.0, 0,
                           None)[0]) == 0
