"""int8-wire gradient all-reduce (parallel/quantized.py).

Pins down: (1) the per-block error bound of one quantization hop, (2) the
collective's agreement with exact pmean within two hops' error, (3) replica
agreement (every rank decodes the same bytes), (4) the small-leaf exact
path, and (5) DP training with int8 gradients still converging through the
product step (make_dp_train_step(grad_reduce="int8")).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from nezha_tpu import ops, optim, parallel
from nezha_tpu.parallel._compat import shard_map
from nezha_tpu.parallel.quantized import (
    _qar_mean,
    quantize_roundtrip,
    quantized_all_reduce_mean,
    quantized_wire_bytes,
)


def test_roundtrip_error_bounded_per_block():
    x = jax.random.normal(jax.random.PRNGKey(0), (7, 331)) * 10.0
    y = quantize_roundtrip(x, block=128)
    # Symmetric int8: error <= amax/(2*127) per block; bound with the
    # global amax (looser but shape-independent).
    bound = float(jnp.abs(x).max()) / 127.0
    assert float(jnp.abs(y - x).max()) <= bound + 1e-6


def test_roundtrip_exact_cases():
    # Zeros and exact grid points survive untouched.
    z = jnp.zeros((130,))
    np.testing.assert_array_equal(np.asarray(quantize_roundtrip(z)), 0.0)
    x = jnp.asarray([127.0, -127.0, 0.0, 1.0] * 32)
    np.testing.assert_allclose(np.asarray(quantize_roundtrip(x, block=128)),
                               np.asarray(x), rtol=1e-6)


def _run_qar(devices8, x_per_rank, block=128):
    mesh = parallel.make_mesh({"dp": 8})
    fn = jax.jit(shard_map(
        lambda x: _qar_mean(x[0], "dp", block)[None],
        mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")))
    return np.asarray(fn(x_per_rank))


def test_matches_exact_mean_within_two_hops(devices8):
    r = np.random.RandomState(0)
    # Ragged size (not a multiple of 8*block) exercises the padding path.
    x = r.randn(8, 1000).astype(np.float32) * 5.0
    got = _run_qar(devices8, jnp.asarray(x))
    want = x.mean(axis=0)
    # Two quantization stages; each bounded by stage amax/127.
    bound = (np.abs(x).max() + np.abs(want).max()) / 127.0
    for rank in range(8):
        assert np.abs(got[rank] - want).max() <= bound + 1e-6


def test_all_ranks_decode_identical_bytes(devices8):
    r = np.random.RandomState(1)
    x = jnp.asarray(r.randn(8, 4096).astype(np.float32))
    got = _run_qar(devices8, x, block=512)
    for rank in range(1, 8):
        np.testing.assert_array_equal(got[0], got[rank])


def test_tree_api_small_leaves_are_exact(devices8):
    mesh = parallel.make_mesh({"dp": 8})
    r = np.random.RandomState(2)
    big = r.randn(8, 8192).astype(np.float32)
    small = r.randn(8, 16).astype(np.float32)
    steps = jnp.tile(jnp.arange(8, dtype=jnp.int32)[:, None], (1, 4))

    def reduce_tree(tree):
        squeezed = jax.tree_util.tree_map(lambda t: t[0], tree)
        out = quantized_all_reduce_mean(squeezed, "dp", block=512,
                                        min_numel=4096)
        return jax.tree_util.tree_map(lambda t: t[None], out)

    fn = jax.jit(shard_map(
        reduce_tree, mesh=mesh,
        in_specs=({"big": P("dp"), "small": P("dp"), "steps": P("dp")},),
        out_specs={"big": P("dp"), "small": P("dp"), "steps": P("dp")}))
    out = fn({"big": jnp.asarray(big), "small": jnp.asarray(small),
              "steps": steps})
    # Small float leaf: bit-exact pmean. Integer leaf: exact psum-mean path.
    np.testing.assert_allclose(np.asarray(out["small"])[0],
                               small.mean(axis=0), rtol=1e-6, atol=1e-6)
    # Big leaf: quantized but close.
    assert np.abs(np.asarray(out["big"])[0] -
                  big.mean(axis=0)).max() <= np.abs(big).max() / 60.0


def test_dp_training_converges_with_int8_grads(devices8):
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.loop import init_train_state

    mesh = parallel.make_mesh({"dp": 8})
    model = MLP(32, (64,), 10)
    opt = optim.sgd(0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    state = parallel.replicate(mesh, state)
    ce = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"]).mean()
    step = parallel.make_dp_train_step(model, opt, ce, mesh,
                                       grad_reduce="int8")
    r = np.random.RandomState(0)
    x = r.randn(64, 32).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    b = parallel.shard_batch(mesh, {"image": jnp.asarray(x),
                                    "label": jnp.asarray(y)})
    losses = []
    for _ in range(40):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.5 * losses[0], losses[::10]


def test_quantized_reduce_scatter_matches_exact(devices8):
    from nezha_tpu.parallel.quantized import quantized_reduce_scatter_mean
    mesh = parallel.make_mesh({"dp": 8})
    r = np.random.RandomState(3)
    # Ragged chunk (8*37 elements -> chunk 37, not block-aligned).
    x = r.randn(8, 8 * 37).astype(np.float32) * 3.0

    def rs(xx, f):
        return f(xx[0])[None]

    exact_fn = jax.jit(shard_map(
        lambda xx: rs(xx, lambda v: jax.lax.psum_scatter(
            v, "dp", scatter_dimension=0, tiled=True) / 8),
        mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")))
    quant_fn = jax.jit(shard_map(
        lambda xx: rs(xx, lambda v: quantized_reduce_scatter_mean(
            v, "dp", block=64)),
        mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")))
    xj = jnp.asarray(x)
    want, got = np.asarray(exact_fn(xj)), np.asarray(quant_fn(xj))
    assert got.shape == want.shape
    assert np.abs(got - want).max() <= np.abs(x).max() / 127.0 + 1e-6


def test_quantized_all_gather_matches_exact(devices8):
    from nezha_tpu.parallel.quantized import quantized_all_gather
    mesh = parallel.make_mesh({"dp": 8})
    r = np.random.RandomState(4)
    x = r.randn(8, 37).astype(np.float32)  # ragged chunk again

    fn = jax.jit(shard_map(
        lambda xx: quantized_all_gather(xx[0], "dp", block=64)[None],
        mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp")))
    got = np.asarray(fn(jnp.asarray(x))).reshape(8, 8 * 37)
    want = x.reshape(-1)
    for rank in range(8):
        assert np.abs(got[rank] - want).max() <= np.abs(x).max() / 127.0 + 1e-6


def test_zero1_training_converges_with_int8_wire(devices8):
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.loop import init_train_state

    mesh = parallel.make_mesh({"dp": 8})
    model = MLP(32, (64,), 10)
    opt = optim.adamw(3e-3)
    base = init_train_state(model, opt, jax.random.PRNGKey(0))
    state = {
        "variables": parallel.replicate(mesh, base["variables"]),
        "opt_state": parallel.zero1_init_opt_state(
            opt, base["variables"]["params"], mesh),
        "rng": parallel.replicate(mesh, base["rng"]),
    }
    ce = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"]).mean()
    step = parallel.make_zero1_train_step(model, opt, ce, mesh,
                                          grad_reduce="int8",
                                          quant_min_numel=64)
    r = np.random.RandomState(0)
    x = r.randn(64, 32).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    b = parallel.shard_batch(mesh, {"image": jnp.asarray(x),
                                    "label": jnp.asarray(y)})
    losses = []
    for _ in range(40):
        state, m = step(state, b)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.5 * losses[0], losses[::10]


def test_rejects_unknown_grad_reduce(devices8):
    from nezha_tpu.models.mlp import MLP
    mesh = parallel.make_mesh({"dp": 8})
    with pytest.raises(ValueError, match="grad_reduce"):
        parallel.make_dp_train_step(MLP(4, (4,), 2), optim.sgd(0.1),
                                    lambda o, b: o.sum(), mesh,
                                    grad_reduce="int4")


def test_wire_bytes_accounting():
    n = 8
    numel = n * 512 * 10
    got = quantized_wire_bytes(numel, block=512, world=n)
    payload = numel * 1 + (numel // 512) * 4
    assert got == int(2 * payload * (n - 1) / n)
    # ~3.9x fewer wire bytes than fp32's 2*(n-1)/n * 4B convention.
    fp32 = 2 * numel * 4 * (n - 1) / n
    assert fp32 / got > 3.8
