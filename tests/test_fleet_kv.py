"""Fleet-wide KV reuse: prefix-affinity routing + peer-to-peer
quantized block pull (the ISSUE 17 acceptance suite).

Layers under test, bottom up: the digest half (blake2b prefix hashing,
bounded recency-first digest build, the lazy ``DigestCache``, healthz
payload parsing), the affinity half (coverage, load-discounted scoring,
consistent-hash cold placement), the scheduler's peer surfaces
(``fleet_digest`` / ``export_prefix`` / ``install_pulled`` with
``origin="peer"`` tagging and bit-identical decode), the Router's
three-tier pick (digest-affinity revisits, pull hints at queue-full
owners, the peer transfer over the ``/kv_export`` int8 wire), the CLI
plumbing, the fleet benchmark scenario, and the chaos acceptance:
SIGKILL the block-owning replica mid-pull and prove typed degradation
to a cold prefill with zero leaks. Fault points drilled here:
``router.affinity`` (scorer degrades to least-loaded, never a client
error) and ``replica.kv_pull`` (pull failure degrades to a cold
prefill, ``kind="kv_pull_failed"``).
"""

import json
import os
import sys
import threading
import time

import pytest

import jax

from nezha_tpu import faults
from nezha_tpu.faults import FaultPlan
from nezha_tpu.serve import (Engine, FinishReason, Request, Scheduler,
                             ServeConfig, fleetcache, migrate)
from nezha_tpu.serve.router import Router
from nezha_tpu.serve.supervisor import (RouterConfig, Supervisor,
                                        ThreadBackend)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _sub in ("tools", "benchmarks"):
    _p = os.path.join(_ROOT, _sub)
    if _p not in sys.path:
        sys.path.insert(0, _p)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def tiny_model():
    from nezha_tpu.cli.train import TINY_GPT2_KW
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    model = GPT2(GPT2Config(**TINY_GPT2_KW))
    return model, model.init(jax.random.PRNGKey(0))


def _engine(tiny_model, **kw):
    model, variables = tiny_model
    base = dict(max_batch_size=2, max_len=64, max_prefill_len=16,
                kv_block_size=8, kv_dtype="int8", queue_capacity=8)
    base.update(kw)
    return Engine(model, variables, ServeConfig(**base))


def _prompt(n, vocab=512, salt=0):
    return [(7 * i + 3 + 11 * salt) % vocab for i in range(n)]


# ------------------------------------------------------- digest hashing
def test_hash_prefix_deterministic_and_incremental():
    toks = _prompt(40)
    h1 = fleetcache.hash_prefix(toks[:8])
    assert h1 == fleetcache.hash_prefix(toks[:8])
    assert len(h1) == 16 and int(h1, 16) >= 0
    # one token differs -> a different hash (tokens never on the wire,
    # yet equal prefixes agree across processes)
    assert h1 != fleetcache.hash_prefix(toks[:7] + [toks[7] ^ 1])
    # the incremental one-pass walk equals per-prefix hashing
    hashes = fleetcache.prefix_hashes(toks, 8)
    assert hashes == [fleetcache.hash_prefix(toks[:8 * (i + 1)])
                      for i in range(5)]
    assert fleetcache.prefix_hashes(toks[:7], 8) == []
    assert fleetcache.prefix_hashes([], 8) == []


def test_digest_payload_build_bound_and_parse(tiny_model):
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    prompt = _prompt(21)
    sched.submit(Request(prompt=prompt, max_new_tokens=4,
                         request_id="d"))
    sched.run_until_idle()
    pay = sched.fleet_digest(interval_s=0.001, max_entries=64)
    assert pay["digest_size"] >= 2 and pay["digest_age_s"] >= 0.0
    parsed = fleetcache.digest_entries_of(pay)
    assert parsed is not None
    bs, entries = parsed
    assert bs == 8
    hashes = fleetcache.prefix_hashes(prompt, bs)
    tiers = dict(entries)
    assert all(h in tiers for h in hashes)
    assert set(tiers.values()) == {"device"}
    # the bound truncates recency-first, it never overflows the wire
    bounded = sched.fleet_digest(interval_s=0.001, max_entries=1)
    assert bounded["digest_size"] == 1
    # parse is defensive: wrong/missing version or malformed entries
    # mean "no digest", never an exception in the router's scorer
    assert fleetcache.digest_entries_of({}) is None
    assert fleetcache.digest_entries_of(
        {"fleet_digest": {"v": 99, "block_size": 8,
                          "entries": []}}) is None
    assert fleetcache.digest_entries_of(
        {"fleet_digest": {"v": fleetcache.DIGEST_VERSION,
                          "block_size": 8,
                          "entries": "nope"}}) is None
    assert fleetcache.digest_entries_of(
        {"fleet_digest": "nope"}) is None
    eng.pool.leak_check()


def test_digest_cache_interval_and_validation():
    with pytest.raises(ValueError):
        fleetcache.DigestCache(interval_s=0.0)
    with pytest.raises(ValueError):
        fleetcache.DigestCache(interval_s=1.0, max_entries=0)


# ----------------------------------------------------- affinity scoring
def test_coverage_longest_first_and_tier():
    hashes = ["a", "b", "c"]
    assert fleetcache.coverage({}, hashes) == (0, None)
    assert fleetcache.coverage({"a": "device"}, hashes) \
        == (1, "device")
    # longest covered prefix wins; the tier reported is the deepest
    # covering entry's
    assert fleetcache.coverage(
        {"a": "device", "b": "host"}, hashes) == (2, "host")
    # the scan is longest-first and trusts the digest to advertise
    # full chains (a trie node implies its ancestors): the deepest
    # hit alone answers in one lookup
    assert fleetcache.coverage({"c": "device"}, hashes) \
        == (3, "device")
    assert fleetcache.coverage({"z": "device"}, hashes) == (0, None)


def test_score_discounts_load_and_place_cold_consistent():
    # more covered tokens -> higher score; more load -> lower score
    assert fleetcache.score(2, 8, 0, 0) > fleetcache.score(1, 8, 0, 0)
    assert fleetcache.score(2, 8, 0, 0) > fleetcache.score(2, 8, 1, 2)
    assert fleetcache.score(0, 8, 0, 0) == 0.0
    toks = _prompt(32)
    rid = fleetcache.place_cold(toks, 8, [0, 1, 2])
    assert rid in (0, 1, 2)
    # deterministic, and independent of candidate ordering
    assert rid == fleetcache.place_cold(toks, 8, [2, 1, 0])
    assert fleetcache.place_cold(toks, 8, []) is None


# --------------------------------------------- scheduler peer surfaces
def test_export_prefix_install_pulled_bit_identical(tiny_model):
    """The peer-transfer halves at scheduler level: A's cached prefix
    exported over the int8 wire installs into B tagged peer, B's
    admission prefix-hits it (a fleet PEER hit), and the decoded
    continuation is bit-identical to A's — the same quantized blocks
    produce the same greedy tokens."""
    a, b = _engine(tiny_model), _engine(tiny_model)
    sa, sb = Scheduler(a), Scheduler(b)
    prompt = _prompt(29)
    sa.submit(Request(prompt=prompt, max_new_tokens=6,
                      request_id="src"))
    sa.run_until_idle()
    ref = sa.results["src"].tokens
    assert len(ref) == 6

    wire = sa.export_prefix(prompt)
    assert wire["nblocks"] == 3 and wire["nbytes"] > 0
    tokens, layers, nbytes = migrate.decode_wire(wire)
    assert tokens == prompt[:24]
    assert sb.install_pulled(tokens, layers, nbytes) == 3
    sb.submit(Request(prompt=prompt, max_new_tokens=6,
                      request_id="dst"))
    sb.run_until_idle()
    assert sb.results["dst"].tokens == ref
    assert b.pool.prefix_hits == 1
    assert b.pool.fleet_hits["peer"] == 1
    assert a.pool.fleet_hits["peer"] == 0
    a.pool.leak_check()
    b.pool.leak_check()


def test_export_prefix_zero_coverage_is_empty_wire(tiny_model):
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    wire = sched.export_prefix(_prompt(21, salt=9))
    assert wire["nblocks"] == 0
    tokens, layers, nbytes = migrate.decode_wire(wire)
    assert tokens == [] and layers == [] and nbytes == 0
    # installing an empty wire is a no-op, not an error
    assert sched.install_pulled(tokens, layers, nbytes) == 0
    eng.pool.leak_check()


# ------------------------------------------------------- cluster layer
def _worker_args(extra=()):
    from nezha_tpu.cli.serve import build_parser
    return build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "64", "--max-prefill-len", "8",
         "--kv-block-size", "8", "--kv-dtype", "int8",
         "--queue-capacity", "8", "--digest-interval", "0.05",
         "--platform", "cpu", *extra])


def _cfg(**kw):
    base = dict(replicas=2, probe_interval_s=0.1, probe_misses=3,
                route_retries=2, retry_backoff_base_s=0.01,
                retry_backoff_max_s=0.05, restart_backoff_base_s=0.05,
                restart_backoff_max_s=0.5, drain_timeout_s=20.0,
                seed=0, affinity_routing=True, digest_interval_s=0.05)
    base.update(kw)
    return RouterConfig(**base)


def _cluster(cfg, extra=()):
    sup = Supervisor(ThreadBackend(_worker_args(extra),
                                   drain_timeout_s=20.0), cfg)
    router = Router(sup, cfg)
    sup.start()
    assert router.wait_live(cfg.replicas, timeout_s=600), sup.describe()
    return sup, router


def _worker_sched(sup, rid):
    return sup.replicas()[rid].handle.worker._sched


def _leak_check_all(sup):
    for r in sup.replicas():
        worker = getattr(r.handle, "worker", None)
        if worker is None or worker.dead.is_set():
            continue
        worker._sched.engine.pool.leak_check()


def _route_ok(router, rid_prompt, req_id, **kw):
    code, obj = router.route({"id": req_id, "prompt_tokens": rid_prompt,
                              "max_new_tokens": 4, **kw})
    assert code == 200, obj
    return obj


def _wait_covered(router, sup, prompt, timeout_s=30.0):
    """Probe until some replica's healthz digest fully covers
    ``prompt``'s whole-block prefix; -> that replica."""
    hashes = fleetcache.prefix_hashes(prompt, 8)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        router.probe_all()
        for r in sup.live_replicas():
            parsed = fleetcache.digest_entries_of(r.last_health)
            if parsed and fleetcache.coverage(
                    parsed[1], hashes)[0] >= len(hashes):
                return r
        time.sleep(0.02)
    raise AssertionError("digest coverage never appeared on /healthz")


def test_cluster_digest_on_healthz_and_affinity_revisit(tiny_model):
    """End to end over real sockets: the /healthz payload carries the
    versioned digest + size/age fields, the prober caches it, and a
    revisit routes back to the owner replica (an affinity win + a
    device-trie hit) instead of the least-loaded default. The
    ``router.affinity`` fault point degrades the scorer to plain
    least-loaded — the request still answers 200."""
    cfg = _cfg()
    sup, router = _cluster(cfg)
    try:
        prompt = _prompt(29)
        first = _route_ok(router, prompt, "fleet-v0")
        owner = _wait_covered(router, sup, prompt)
        pay = owner.last_health
        assert pay["digest_size"] >= 3 and pay["digest_age_s"] >= 0.0
        assert pay["fleet_digest"]["v"] == fleetcache.DIGEST_VERSION
        assert pay["fleet_digest"]["block_size"] == 8

        wins0 = router.affinity_wins
        osched = _worker_sched(sup, owner.rid)
        hits0 = osched.engine.pool.prefix_hits
        again = _route_ok(router, prompt, "fleet-v1")
        assert again["tokens"] == first["tokens"]
        assert osched.engine.pool.prefix_hits == hits0 + 1
        assert osched.engine.pool.fleet_hits["device"] >= 1
        # the win ledger counts only picks that beat least-loaded; the
        # cold placement may already have owned rid 0, so >= not ==
        assert router.affinity_wins >= wins0

        # fault drill: the scorer trips, the pick degrades, 200 anyway
        faults.install(FaultPlan.parse("router.affinity:error@1"))
        deg = _route_ok(router, prompt, "fleet-v2")
        assert deg["tokens"] == first["tokens"]
        assert deg.get("fleet_pull") is None
        faults.clear()
        _leak_check_all(sup)
    finally:
        faults.clear()
        router.stop()
        sup.shutdown()


def test_cluster_peer_pull_from_saturated_owner(tiny_model):
    """The tentpole drill: the owner's admission queue is full, so the
    router places the revisit on the sibling WITH a pull_from pointer;
    the blocks arrive over /kv_export, install tagged peer, and the
    decoded output is bit-identical to the owner's. A second pass with
    ``replica.kv_pull`` tripped proves pull failure degrades to a cold
    prefill (typed ``kv_pull_failed``) with the same output and no
    client-visible error."""
    cfg = _cfg()
    sup, router = _cluster(cfg)
    try:
        prompt = _prompt(29, salt=3)
        first = _route_ok(router, prompt, "pull-v0")
        owner = _wait_covered(router, sup, prompt)
        sibling = next(r for r in sup.live_replicas()
                       if r.rid != owner.rid)
        osched = _worker_sched(sup, owner.rid)
        ssched = _worker_sched(sup, sibling.rid)
        cap = osched.queue_capacity
        try:
            osched.queue_capacity = 0       # deterministic saturation
            pulls0, bytes0 = router.kv_pulls, router.kv_pull_bytes
            obj = _route_ok(router, prompt, "pull-v1")
            fp = obj["fleet_pull"]
            assert fp["installed"] == 3 and fp["blocks"] == 3
            assert fp["bytes"] > 0 and fp["seconds"] >= 0
            assert obj["tokens"] == first["tokens"]
            assert router.kv_pulls == pulls0 + 1
            assert router.kv_pull_bytes == bytes0 + fp["bytes"]
            assert ssched.engine.pool.fleet_hits["peer"] == 1

            # pull-failure drill: blocks already installed on the
            # sibling would mask the cold path — use a fresh prefix
            # the sibling has never seen
            prompt2 = _prompt(29, salt=4)
            osched.queue_capacity = cap
            ref2 = _route_ok(router, prompt2, "pull2-v0",
                             )
            owner2 = _wait_covered(router, sup, prompt2)
            osched2 = _worker_sched(sup, owner2.rid)
            cap2 = osched2.queue_capacity
            try:
                osched2.queue_capacity = 0
                faults.install(
                    FaultPlan.parse("replica.kv_pull:error@1"))
                deg = _route_ok(router, prompt2, "pull2-v1")
                fp2 = deg["fleet_pull"]
                assert fp2["installed"] == 0
                assert fp2["error_type"] == "kv_pull_failed"
                assert "injected" in fp2["degraded"]
                assert deg["tokens"] == ref2["tokens"]
                assert router.kv_pulls == pulls0 + 1   # nothing committed
            finally:
                osched2.queue_capacity = cap2
        finally:
            osched.queue_capacity = cap
        faults.clear()
        _leak_check_all(sup)
    finally:
        faults.clear()
        router.stop()
        sup.shutdown()


def test_chaos_kill_owner_mid_pull_degrades_cold(tiny_model):
    """THE chaos acceptance: SIGKILL the block-owning replica while the
    sibling is mid-pull (an injected delay stretches the transfer
    window the kill lands inside). The request still answers 200 — the
    pull degrades typed to a cold prefill — the output matches the
    cold reference bit for bit, and every surviving pool balances its
    books."""
    cfg = _cfg()
    sup, router = _cluster(cfg)
    try:
        prompt = _prompt(29, salt=5)
        ref = _route_ok(router, prompt, "chaos-v0")
        owner = _wait_covered(router, sup, prompt)
        osched = _worker_sched(sup, owner.rid)
        osched.queue_capacity = 0
        # stretch the pull window, then kill the source inside it
        faults.install(FaultPlan.parse("replica.kv_pull:delay=1.5@1"))
        result = {}

        def client():
            result["resp"] = router.route(
                {"id": "chaos-v1", "prompt_tokens": prompt,
                 "max_new_tokens": 4})

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.5)                    # inside the delayed pull
        sup.kill(owner.rid)
        t.join(timeout=600)
        assert not t.is_alive()
        code, obj = result["resp"]
        assert code == 200, obj
        fp = obj["fleet_pull"]
        assert fp["installed"] == 0
        assert fp["error_type"] == "kv_pull_failed"
        assert obj["tokens"] == ref["tokens"]
        faults.clear()
        assert router.wait_live(2, timeout_s=600), sup.describe()
        _leak_check_all(sup)
    finally:
        faults.clear()
        router.stop()
        sup.shutdown()


# -------------------------------------------------------- CLI plumbing
def test_cli_flags_and_worker_passthrough():
    from nezha_tpu.cli.serve import _worker_argv, build_parser
    args = build_parser().parse_args(["--random-init"])
    assert args.affinity_routing is None      # resolved per topology
    assert args.digest_interval == 2.0
    assert args.digest_max_entries == 256
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--affinity-routing", "maybe"])
    args = build_parser().parse_args(
        ["--random-init", "--digest-interval", "0.5",
         "--digest-max-entries", "32"])
    argv = _worker_argv(args, rid=0, port=9999)
    assert argv[argv.index("--digest-interval") + 1] == "0.5"
    assert argv[argv.index("--digest-max-entries") + 1] == "32"


def test_router_config_digest_validation():
    with pytest.raises(ValueError, match="digest_interval_s"):
        RouterConfig(replicas=2, digest_interval_s=0.0)
    with pytest.raises(ValueError, match="digest_max_entries"):
        RouterConfig(replicas=2, digest_max_entries=0)
    cfg = RouterConfig(replicas=2, digest_interval_s=0.2,
                       probe_interval_s=0.1)
    assert cfg.digest_stale_s == pytest.approx(0.6)


# ------------------------------------------------------ bench + gates
def test_serving_benchmark_fleet_record(tiny_model):
    """benchmarks/serving.py --replicas + --churn-users: the fleet
    record carries the first/revisit TTFT split, the affinity-win and
    pull ledgers, and the peer drill commits a pull against the
    queue-clamped owner."""
    import serving as bench

    rec = bench.run(bench.build_parser().parse_args(
        ["--replicas", "2", "--requests", "4", "--concurrency", "1",
         "--churn-users", "2", "--churn-prefix-len", "16",
         "--kv-block-size", "16", "--kv-dtype", "int8",
         "--kv-num-blocks", "8", "--max-batch-size", "2",
         "--max-prefill-len", "8", "--max-len", "48",
         "--max-new-tokens", "4", "--sample-fraction", "0",
         "--queue-capacity", "8", "--digest-interval", "0.1"]))
    fl = rec["fleet"]
    assert fl["users"] == 2 and fl["visits"] == 2
    assert fl["affinity_routing"] == "on"
    assert fl["ttft_first_visit_s"]["p50"] > 0
    assert fl["ttft_revisit_s"]["p50"] > 0
    assert fl["fleet_hits"]["device"] >= 2     # both revisits warm
    peer = fl["peer_pull"]
    assert peer["saturated"] is True
    assert peer["installed"] == 1 and peer["bytes"] > 0
    assert fl["kv_pulls"] == 1
    assert fl["kv_pull_bytes"] == peer["bytes"]
    # misaligned churn prefixes are a typed refusal in fleet mode too
    with pytest.raises(SystemExit, match="multiple"):
        bench.run(bench.build_parser().parse_args(
            ["--replicas", "2", "--churn-users", "2",
             "--churn-prefix-len", "10", "--kv-block-size", "16",
             "--kv-dtype", "int8"]))


def test_nezha_bench_fleet_kv_gate_rows():
    """The fleet_kv gate logic (no model run — cooked results): the
    revisit-vs-cold ratio is a HARD gate at 0.7; affinity wins,
    committed pulls, and peer-installed blocks must be nonzero; a
    committed baseline adds a drift gate."""
    from nezha_tpu.cli import bench as nb

    good = {"fleet_kv": {"revisit_vs_first_ttft_p50": 0.45,
                         "affinity_wins": 8, "kv_pulls": 1,
                         "peer_installed": 2}}
    rows = nb._gate(good, {}, "cpu", 0.30)["serving"]
    assert rows["fleet_kv.revisit_vs_first_ttft_p50"]["ok"]
    assert rows["fleet_kv.affinity_wins"]["ok"]
    assert rows["fleet_kv.kv_pulls"]["ok"]
    assert rows["fleet_kv.peer_installed"]["ok"]

    bad = {"fleet_kv": {"revisit_vs_first_ttft_p50": 0.9,
                        "affinity_wins": 0, "kv_pulls": 0,
                        "peer_installed": 0}}
    rows = nb._gate(bad, {}, "cpu", 0.30)["serving"]
    assert not rows["fleet_kv.revisit_vs_first_ttft_p50"]["ok"]
    assert not rows["fleet_kv.affinity_wins"]["ok"]
    assert not rows["fleet_kv.kv_pulls"]["ok"]
    assert not rows["fleet_kv.peer_installed"]["ok"]

    base = {"by_platform": {"cpu": {
        "fleet_kv": {"revisit_vs_first_ttft_p50": 0.36}}}}
    rows = nb._gate(good, {"serving": base}, "cpu", 0.30)["serving"]
    drift = rows["fleet_kv.revisit_vs_first_ttft_p50_vs_baseline"]
    assert drift["ok"]                      # 0.45/0.36 = 1.25 <= 1.30
    rows = nb._gate(good, {"serving": base}, "cpu", 0.10)["serving"]
    assert not rows[
        "fleet_kv.revisit_vs_first_ttft_p50_vs_baseline"]["ok"]
