"""Serving stack: slot pool, per-row sampling, the frozen-program engine
(1 + len(prefill_buckets) compiled programs), bucketed + chunked
prefill, scheduler edge cases (queue-full backpressure, EOS retirement +
same-iteration admission, per-row isolation, deadlines, validation
before slot allocation), and the serving telemetry artifacts.
Everything runs the tiny CPU GPT-2 from tests/test_generate.py's
config — tier-1 budget is tight, and the engine's whole point is that
the program set compiles once per bucket and never again."""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu.models.generate import generate
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import (
    Engine,
    QueueFull,
    Request,
    Scheduler,
    ServeConfig,
    SlotPool,
    sample_tokens,
)

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
SCFG = ServeConfig(max_batch_size=3, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=4,
                   cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def engine(model_and_vars):
    """ONE engine for the whole module: its program set (step + one
    prefill per bucket) compiles once and every test reuses it (the
    serving property under test)."""
    model, variables = model_and_vars
    return Engine(model, variables, SCFG)


def _drain(sched, max_iters=200):
    iters = sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"
    return iters


# ------------------------------------------------------------- slot pool
def test_slot_pool_alloc_free(model_and_vars):
    model, _ = model_and_vars
    pool = SlotPool(model, capacity=2, max_len=8, dtype=jnp.float32)
    a, b = pool.alloc(), pool.alloc()
    assert {a, b} == {0, 1} and pool.alloc() is None
    assert pool.num_active == 2 and pool.occupancy == 1.0
    pool.free(a)
    assert pool.num_free == 1 and pool.alloc() == a
    with pytest.raises(ValueError, match="double free"):
        pool.free(b)
        pool.free(b)
    with pytest.raises(ValueError, match="out of range"):
        pool.free(7)
    assert pool.caches[0]["k"].shape == (2, CFG["num_heads"], 8,
                                         CFG["hidden_size"]
                                         // CFG["num_heads"])


# ------------------------------------------------------ per-row sampling
def test_sample_tokens_per_row_params():
    logits = jnp.asarray([[5.0, 4.0, 3.0, 2.0, 1.0]] * 4, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4, dtype=jnp.uint32))
    # row 0 greedy, row 1 top-k=1 (forced argmax), row 2 nucleus p->0
    # (degrades to argmax), row 3 unconstrained sampling.
    for seed in range(10):
        keys = jax.vmap(jax.random.PRNGKey)(
            jnp.arange(4, dtype=jnp.uint32) + seed * 7)
        tok = np.asarray(sample_tokens(
            logits, keys,
            temperature=jnp.asarray([0.0, 1.0, 1.0, 1.0]),
            top_k=jnp.asarray([0, 1, 0, 0], jnp.int32),
            top_p=jnp.asarray([1.0, 1.0, 1e-6, 1.0]),
            k_max=4))
        assert tok[0] == 0 and tok[1] == 0 and tok[2] == 0
        assert 0 <= tok[3] < 5

    # per-row k under the static cap: k=2 rows never leave the top-2 set
    # even when a batch neighbor samples the full vocab.
    seen = set()
    for seed in range(50):
        keys = jax.vmap(jax.random.PRNGKey)(
            jnp.arange(2, dtype=jnp.uint32) + seed * 13)
        tok = np.asarray(sample_tokens(
            jnp.asarray([[1.0, 2.0, 3.0, 2.5, 0.0]] * 2, jnp.float32),
            keys, temperature=jnp.asarray([2.0, 2.0]),
            top_k=jnp.asarray([2, 0], jnp.int32),
            top_p=jnp.asarray([1.0, 1.0]), k_max=4))
        seen.add(int(tok[0]))
    assert seen <= {2, 3}, seen  # the two largest logits

    with pytest.raises(ValueError, match="k_max"):
        sample_tokens(logits, keys[:4], jnp.zeros(4),
                      jnp.zeros(4, jnp.int32), jnp.ones(4), k_max=99)


# ------------------------------------------------------- scheduler edges
def test_queue_full_rejection(engine):
    sched = Scheduler(engine)
    for _ in range(SCFG.queue_capacity):
        sched.submit(Request(prompt=[1, 2], max_new_tokens=2))
    with pytest.raises(QueueFull):
        sched.submit(Request(prompt=[1, 2], max_new_tokens=2))
    _drain(sched)

    # The admission limit is the slot's KV capacity, NOT the prefill
    # width — a 20-token prompt (> max_prefill_len=8) is admissible
    # (chunked prefill); only max_len bounds what can be served.
    with pytest.raises(ValueError, match="exceeds max_len"):
        sched.submit(Request(prompt=list(range(1, 48)), max_new_tokens=2))
    with pytest.raises(ValueError, match="exceeds max_len"):
        sched.submit(Request(prompt=[1, 2], max_new_tokens=100))
    with pytest.raises(ValueError, match="non-empty"):
        sched.submit(Request(prompt=[], max_new_tokens=2))


def test_rejected_request_never_consumes_slot(engine):
    """Validation is hoisted into admission: a bad request bounces at
    submit() with no slot held, no queue entry, and no program run."""
    sched = Scheduler(engine)
    free_before = engine.pool.num_free
    stats_before = engine.compile_stats()
    for bad in (Request(prompt=[1, 2, 999], max_new_tokens=2),  # id range
                Request(prompt=[-1], max_new_tokens=2),
                Request(prompt=list(range(1, 48)), max_new_tokens=2),
                Request(prompt=[1], max_new_tokens=0)):
        with pytest.raises(ValueError):
            sched.submit(bad)
    assert engine.pool.num_free == free_before
    assert sched.queue_depth == 0 and not sched.has_work()
    # No prefill/step program was even dispatched for the rejects.
    assert engine.compile_stats() == stats_before


def test_deadline_expiry_of_queued_request(engine):
    sched = Scheduler(engine)
    # Capacity 3: occupy every slot with long decodes, then queue one
    # request with an already-hopeless deadline.
    for i in range(SCFG.max_batch_size):
        sched.submit(Request(prompt=[5, 17], max_new_tokens=12,
                             request_id=f"long-{i}"))
    rid = sched.submit(Request(prompt=[1, 2], max_new_tokens=4,
                               deadline_s=0.0, request_id="doomed"))
    sched.step()
    res = sched.results[rid]
    assert res.finish_reason == "deadline"
    assert res.tokens == [] and res.ttft_s is None
    _drain(sched)


def test_eos_retirement_admits_waiter_same_iteration(engine):
    # Learn a seed-deterministic SAMPLED continuation (greedy repeats one
    # token on this random init), then plant its first fresh token as
    # EOS — the request must retire right there on the replay.
    probe_kw = dict(prompt=[5, 17, 3, 42], max_new_tokens=8,
                    temperature=0.9, top_k=10, seed=7)
    sched = Scheduler(engine)
    probe = sched.submit(Request(**probe_kw))
    _drain(sched)
    seq = sched.results[probe].tokens
    stop = next(i for i in range(1, len(seq)) if seq[i] not in seq[:i])
    eos, ref = seq[stop], seq[:stop + 1]
    # Fill all 3 slots; the EOS request retires first and must hand its
    # slot to the queued waiter WITHIN the same scheduler iteration.
    sched.submit(Request(prompt=[7, 7, 23], max_new_tokens=12,
                         request_id="long-a"))
    sched.submit(Request(prompt=[1, 2, 3], max_new_tokens=12,
                         request_id="long-b"))
    rid = sched.submit(Request(**probe_kw, eos_id=eos,
                               request_id="eos-req"))
    waiter = sched.submit(Request(prompt=[9, 9], max_new_tokens=2,
                                  request_id="waiter"))
    while rid not in sched.results:
        assert sched.step() > 0
        live_ids = {lv.request_id for lv in sched._live.values()}
        if rid not in sched.results:
            assert waiter not in live_ids  # no free slot before EOS
    res = sched.results[rid]
    assert res.finish_reason == "eos"
    assert res.tokens == ref  # ends WITH the eos token
    # Same iteration: the retiring step's trailing admit filled the slot.
    live_ids = {lv.request_id for lv in sched._live.values()}
    assert waiter in live_ids
    assert engine.pool.num_active == 3
    _drain(sched)


def test_per_row_sampling_isolation(engine):
    """A greedy request's tokens are bit-identical whether it runs alone
    or next to a temperature-1.0 neighbor (per-row RNG keys, per-row
    params: nothing leaks across slots)."""
    sched = Scheduler(engine)
    alone = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=10))
    _drain(sched)
    solo_tokens = sched.results[alone].tokens

    paired = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=10))
    sched.submit(Request(prompt=[8, 1, 4], max_new_tokens=10,
                         temperature=1.0, seed=11))
    sched.submit(Request(prompt=[2, 2], max_new_tokens=10,
                         temperature=1.0, top_k=5, seed=23))
    _drain(sched)
    assert sched.results[paired].tokens == solo_tokens

    # Sampling is seed-deterministic per request, also regardless of mix.
    a = sched.submit(Request(prompt=[4, 4, 4], max_new_tokens=6,
                             temperature=0.9, top_k=10, seed=7))
    _drain(sched)
    b = sched.submit(Request(prompt=[4, 4, 4], max_new_tokens=6,
                             temperature=0.9, top_k=10, seed=7))
    c = sched.submit(Request(prompt=[4, 4, 4], max_new_tokens=6,
                             temperature=0.9, top_k=10, seed=8))
    _drain(sched)
    assert sched.results[a].tokens == sched.results[b].tokens
    assert sched.results[b].tokens != sched.results[c].tokens


# ------------------------------------ e2e smoke + the frozen program set
def test_serving_smoke_program_count_and_artifacts(model_and_vars,
                                                   tmp_path):
    """The acceptance smoke: ≥3 concurrent requests with different
    sampling params and lengths, a LATE request admitted while earlier
    ones still decode (continuous batching observable via the occupancy
    gauge), greedy rows matching one-shot generate() token-for-token —
    and steady state compiles exactly ``1 + len(prefill_buckets)``
    programs (the batched step + one prefill per bucket), pinned through
    the obs compile-cache counters and FROZEN once every bucket has been
    warmed. The run dir must pass the frozen serving schema and render a
    serving report."""
    import os
    import sys

    from nezha_tpu import obs

    model, variables = model_and_vars
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir, meta={"kind": "serve_test"})
    try:
        engine = Engine(model, variables, SCFG)  # fresh compile counters
        sched = Scheduler(engine)
        r1 = sched.submit(Request(prompt=[5, 17, 3, 42],
                                  max_new_tokens=10))
        r2 = sched.submit(Request(prompt=[7, 7, 23], max_new_tokens=5,
                                  temperature=1.0, top_k=10, seed=3))
        r3 = sched.submit(Request(prompt=[1, 2, 3, 4, 5],
                                  max_new_tokens=7, temperature=0.8,
                                  top_p=0.9, seed=9))
        for _ in range(3):
            sched.step()
        # All three in flight, none finished: continuous batch is full.
        assert engine.pool.num_active == 3
        assert obs.gauge("serve.batch_occupancy").value == 1.0
        # r2 (5 tokens) retires first; the LATE request then joins while
        # r1/r3 are still decoding.
        late = sched.submit(Request(prompt=[6, 5], max_new_tokens=4,
                                    request_id="late"))
        while r2 not in sched.results:
            sched.step()
        live = {lv.request_id for lv in sched._live.values()}
        assert "late" in live and r1 not in sched.results
        assert engine.pool.num_active == 3  # refilled, mid-flight
        _drain(sched)

        # Greedy row == one-shot generate, token for token.
        ref = np.asarray(generate(
            model, variables, np.asarray([[5, 17, 3, 42]], np.int32),
            max_new_tokens=10, temperature=0.0,
            cache_dtype=jnp.float32))[0, 4:]
        assert sched.results[r1].tokens == ref.tolist()
        assert len(sched.results[r3].tokens) == 7

        # Exactly 1 + len(prefill_buckets) compiled programs for the
        # whole mixed-request run (prompt lengths 4/3/5/2 hit both the
        # 4- and 8-buckets), by the engine's own cache AND the
        # process-wide obs counters.
        n_programs = 1 + len(SCFG.prefill_buckets)
        stats = engine.compile_stats()
        assert stats == {"entries": n_programs,
                         "hits": stats["hits"], "misses": n_programs}
        assert stats["hits"] > 10
        assert obs.counter("compile_cache.misses").value == n_programs

        # Warmed means FROZEN: another mixed batch (including a chunked
        # 13-token prompt, which must reuse the bucket programs at
        # advancing offsets) adds hits, never misses.
        f1 = sched.submit(Request(prompt=[3, 1, 4], max_new_tokens=3))
        f2 = sched.submit(Request(prompt=list(range(2, 15)),
                                  max_new_tokens=3))
        _drain(sched)
        assert len(sched.results[f2].tokens) == 3
        stats2 = engine.compile_stats()
        assert stats2["entries"] == n_programs
        assert stats2["misses"] == n_programs
        assert stats2["hits"] > stats["hits"]

        assert obs.counter("serve.admitted_total").value == 6
        assert obs.counter("serve.retired_total").value == 6
        assert obs.counter("serve.tokens_total").value == \
            sum(len(sched.results[r].tokens)
                for r in (r1, r2, r3, "late", f1, f2))
        assert obs.histogram("serve.ttft_s").count == 6
        # Bucket telemetry: 5 single-chunk prefills + a 2-chunk prefill
        # (13 = 8 + a 5-tail in the 8-bucket) = 7 chunk calls.
        assert obs.counter("serve.prefill.chunks_total").value == 7
        assert obs.histogram("serve.prefill.bucket_len").count == 7
    finally:
        obs.end_run()

    # Frozen serving schema + report rendering.
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "serving:" in report and "ttft" in report and "tpot" in report
    assert "6 admitted" in report
    # Bucket-occupancy line, labeled with the active prefill impl
    # (CPU auto resolves to the composed XLA path) and the chunk
    # parallelism mode (replicated = classic, seq xM = sequence-
    # sharded over a mesh).
    assert "prefill[xla, replicated]: 7 chunk(s)" in report

    # Every batched decode step is labeled with its own span.
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        span_names = {json.loads(ln)["name"] for ln in f if ln.strip()}
    assert "serve.decode_attention" in span_names
    assert "serve.prefill" in span_names

    # The schema checker actually pins the serve names: dropping one
    # histogram from the summary must fail.
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    del summary["histograms"]["serve.ttft_s"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.ttft_s" in e for e in check_run_dir(run_dir))


# --------------------------------------- bucketed and chunked prefill
def test_bucketed_prefill_matches_single_bucket(model_and_vars, engine):
    """A 3-token prompt lands in the 4-bucket on the module engine and
    in the 8-bucket on a single-bucket engine (the old padded-to-
    max_prefill_len behavior) — greedy tokens must be identical: the
    bucket is a pad width, never a semantic."""
    model, variables = model_and_vars
    wide = Engine(model, variables, ServeConfig(
        max_batch_size=1, max_len=48, max_prefill_len=8,
        prefill_buckets=(8,), cache_dtype=jnp.float32))
    prompt = [5, 17, 3]
    out = {}
    for name, eng in (("bucketed", engine), ("padded", wide)):
        sched = Scheduler(eng)
        rid = sched.submit(Request(prompt=prompt, max_new_tokens=8))
        _drain(sched)
        out[name] = sched.results[rid].tokens
    assert out["bucketed"] == out["padded"]
    ref = np.asarray(generate(
        model, variables, np.asarray([prompt], np.int32),
        max_new_tokens=8, cache_dtype=jnp.float32))[0, len(prompt):]
    assert out["bucketed"] == ref.tolist()


def test_chunked_long_prompt_matches_single_shot(model_and_vars, engine):
    """A prompt longer than max_prefill_len (20 > 8: two full 8-chunks
    + a 4-tail) prefills in successive chunks at traced offsets and must
    decode exactly like a single-shot prefill of the same prompt — both
    against an engine whose max_prefill_len covers it in one program,
    and against one-shot generate()."""
    model, variables = model_and_vars
    prompt = [(7 * i + 3) % 97 for i in range(20)]
    sched = Scheduler(engine)                   # max_prefill_len=8
    rid = sched.submit(Request(prompt=prompt, max_new_tokens=6))
    _drain(sched)
    chunked = sched.results[rid].tokens

    single = Engine(model, variables, ServeConfig(
        max_batch_size=1, max_len=48, max_prefill_len=32,
        prefill_buckets=(32,), cache_dtype=jnp.float32))
    sched1 = Scheduler(single)
    rid1 = sched1.submit(Request(prompt=prompt, max_new_tokens=6))
    _drain(sched1)
    assert chunked == sched1.results[rid1].tokens

    ref = np.asarray(generate(
        model, variables, np.asarray([prompt], np.int32),
        max_new_tokens=6, cache_dtype=jnp.float32))[0, len(prompt):]
    assert chunked == ref.tolist()


def test_chunked_tail_never_spills_past_capacity(model_and_vars):
    """max_len NOT a multiple of max_prefill_len + a near-capacity
    prompt: the padded tail chunk would write past the slot's KV
    capacity (dynamic_update_slice clamps the start — silent prefix
    corruption); the engine must slide the tail window back over real
    tokens instead. Greedy output still matches one-shot generate()."""
    model, variables = model_and_vars
    eng = Engine(model, variables, ServeConfig(
        max_batch_size=1, max_len=50, max_prefill_len=8,
        prefill_buckets=(8,), cache_dtype=jnp.float32))
    prompt = [(11 * i + 5) % 97 for i in range(49)]   # 6 full chunks + 1
    sched = Scheduler(eng)
    rid = sched.submit(Request(prompt=prompt, max_new_tokens=1))
    _drain(sched)
    ref = np.asarray(generate(
        model, variables, np.asarray([prompt], np.int32),
        max_new_tokens=1, cache_dtype=jnp.float32))[0, len(prompt):]
    assert sched.results[rid].tokens == ref.tolist()


def test_default_buckets_and_validation():
    from nezha_tpu.serve.engine import default_prefill_buckets
    assert default_prefill_buckets(32) == (8, 16, 32)
    assert default_prefill_buckets(24) == (8, 16, 24)
    assert default_prefill_buckets(8) == (8,)
    assert default_prefill_buckets(5) == (5,)
    assert ServeConfig(max_prefill_len=32).prefill_buckets == (8, 16, 32)
    with pytest.raises(ValueError, match="end exactly"):
        ServeConfig(max_prefill_len=16, prefill_buckets=(4, 8))
    with pytest.raises(ValueError, match="strictly increasing"):
        ServeConfig(max_prefill_len=16, prefill_buckets=(8, 4, 16))
    with pytest.raises(ValueError, match="decode_impl"):
        ServeConfig(decode_impl="pallas")


def test_engine_rejects_bad_shapes(model_and_vars):
    model, variables = model_and_vars
    with pytest.raises(ValueError, match="max_positions"):
        Engine(model, variables, ServeConfig(max_len=1024))
    with pytest.raises(ValueError, match="max_prefill_len"):
        ServeConfig(max_len=8, max_prefill_len=16)
    with pytest.raises(ValueError, match="decode_horizon"):
        ServeConfig(decode_horizon=0)


# ------------------------------------------------- decode horizon (PR 5)
def test_decode_horizon_parity_bit_identical(model_and_vars):
    """horizon=8 delivers bit-identical per-request outputs to horizon=1
    — for a greedy row, a sampled row (RNG streams advance per EMITTED
    token, so they are horizon-invariant), and a chunked-prompt row —
    and the greedy row matches one-shot generate() token for token.
    max_new_tokens=10 with H=8 also exercises the on-device budget
    stopping a block mid-horizon (8 + 2)."""
    model, variables = model_and_vars
    outs = {}
    for h in (1, 8):
        eng = Engine(model, variables,
                     dataclasses.replace(SCFG, decode_horizon=h))
        sched = Scheduler(eng)
        a = sched.submit(Request(prompt=[5, 17, 3, 42],
                                 max_new_tokens=10))
        b = sched.submit(Request(prompt=[7, 7], max_new_tokens=9,
                                 temperature=0.9, top_k=10, seed=7))
        c = sched.submit(Request(prompt=list(range(2, 15)),
                                 max_new_tokens=5))
        _drain(sched)
        outs[h] = {k: (sched.results[k].tokens,
                       sched.results[k].finish_reason)
                   for k in (a, b, c)}
    assert outs[1] == outs[8]
    ref = np.asarray(generate(
        model, variables, np.asarray([[5, 17, 3, 42]], np.int32),
        max_new_tokens=10, temperature=0.0,
        cache_dtype=jnp.float32))[0, 4:]
    greedy_tokens = list(outs[8].values())[0][0]
    assert greedy_tokens == ref.tolist()


def test_eos_mid_horizon_stops_kv_writes_and_overshoot(model_and_vars):
    """A row whose EOS lands at scan step k < H flips the carried done
    mask ON DEVICE: its emitted count stops at k+1, its cache position
    freezes there (no K/V appended for the rest of the block), the
    block's overshoot columns are pad — and through the scheduler the
    client sees tokens ending exactly at the EOS, never overshoot."""
    model, variables = model_and_vars
    cfg8 = dataclasses.replace(SCFG, decode_horizon=8)
    eng = Engine(model, variables, cfg8)
    # Learn a seed-deterministic SAMPLED continuation (distinct tokens;
    # greedy repeats one token on this random init), then plant a
    # mid-horizon token as EOS on the replay.
    kw = dict(prompt=[5, 17, 3, 42], max_new_tokens=8, temperature=0.9,
              top_k=10, seed=7)
    sched = Scheduler(eng)
    probe = sched.submit(Request(**kw))
    _drain(sched)
    seq = sched.results[probe].tokens
    stop = next(i for i in range(1, len(seq)) if seq[i] not in seq[:i])
    eos, ref = seq[stop], seq[:stop + 1]
    assert 1 <= stop < 7          # genuinely mid-horizon

    # Engine-level: one block, device-side stop.
    eng2 = Engine(model, variables, cfg8)
    eng2.prefill(0, kw["prompt"], seed=7, temperature=0.9, top_k=10,
                 eos_id=eos, max_new_tokens=8)
    active = np.zeros((SCFG.max_batch_size,), bool)
    active[0] = True
    tok, emitted = eng2.step(active)
    assert tok.shape == (SCFG.max_batch_size, 8)
    assert emitted[0] == stop + 1
    assert tok[0, :stop + 1].tolist() == ref    # ends WITH the eos
    # Overshoot columns are pad, sampled by nobody.
    assert (tok[0, stop + 1:] == SCFG.pad_id).all()
    # Inactive rows emit nothing.
    assert (emitted[1:] == 0).all()
    # KV writes stopped with the done flip: the cache position froze at
    # prompt + emitted instead of advancing through the whole block.
    assert int(np.asarray(eng2.positions)[0]) == len(kw["prompt"]) + stop + 1

    # Scheduler-level: the client never sees overshoot.
    sched2 = Scheduler(eng)
    rid = sched2.submit(Request(**kw, eos_id=eos))
    _drain(sched2)
    res = sched2.results[rid]
    assert res.finish_reason == "eos"
    assert res.tokens == ref


def test_horizon_frozen_programs_and_dispatch_amortization(
        model_and_vars):
    """horizon > 1 keeps the '1 step + len(prefill_buckets) programs,
    frozen after warmup' contract (the horizon is baked INTO the one
    step program), decodes bit-identically — and performs <= 1/8 the
    host dispatches per token of horizon=1, by the engine's own
    dispatch counter (the acceptance bound of ISSUE 5)."""
    model, variables = model_and_vars
    steps, tokens, all_tokens = {}, {}, {}
    n_programs = 1 + len(SCFG.prefill_buckets)
    for h in (1, 8):
        eng = Engine(model, variables,
                     dataclasses.replace(SCFG, decode_horizon=h))
        sched = Scheduler(eng)
        # Alternate prompt lengths 3/6 so BOTH prefill buckets (4, 8)
        # compile and the frozen-program assertion covers the full set.
        rids = [sched.submit(Request(
                    prompt=[3 + i, 1, 4] * (1 + i % 2),
                    max_new_tokens=16, request_id=f"r{i}"))
                for i in range(4)]
        _drain(sched)
        stats = eng.compile_stats()
        assert stats["entries"] == n_programs
        assert stats["misses"] == n_programs     # frozen after warmup
        steps[h] = eng.step_calls
        all_tokens[h] = {r: sched.results[r].tokens for r in rids}
        tokens[h] = sum(len(t) for t in all_tokens[h].values())
    assert all_tokens[1] == all_tokens[8]
    assert tokens[1] == tokens[8] == 64
    # <= 1/8 of the dispatches per token (4 requests x 16 tokens over
    # batch 3: 32 single-token dispatches vs 4 blocks of 8).
    assert steps[8] / tokens[8] <= (steps[1] / tokens[1]) / 8


def test_horizon_telemetry_host_gap_and_horizon_hist(model_and_vars,
                                                     tmp_path):
    """The two PR-5 instruments: serve.host_gap_s (host time between
    consecutive step dispatches) and serve.decode.horizon (tokens-per-
    dispatch ceiling) land in the run artifacts, pass the pinned schema,
    and render as the report's host-gap line."""
    import os
    import sys

    from nezha_tpu import obs

    model, variables = model_and_vars
    run_dir = str(tmp_path / "hrun")
    obs.start_run(run_dir, meta={"kind": "serve_test"})
    try:
        eng = Engine(model, variables,
                     dataclasses.replace(SCFG, decode_horizon=4))
        sched = Scheduler(eng)
        for i in range(3):
            sched.submit(Request(prompt=[1 + i, 2], max_new_tokens=8))
        _drain(sched)
        # 8 tokens at H=4 = 2 blocks -> at least one inter-dispatch gap.
        assert obs.histogram("serve.host_gap_s").count >= 1
        dh = obs.histogram("serve.decode.horizon")
        assert dh.count == eng.step_calls
        assert dh.summary()["max"] == 4
    finally:
        obs.end_run()
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "host gap" in report and "horizon p50 4" in report
    # The schema checker actually pins the new names.
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    del summary["histograms"]["serve.host_gap_s"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.host_gap_s" in e for e in check_run_dir(run_dir))


def test_horizon_tpot_accounting_block_dt_split(model_and_vars,
                                                tmp_path):
    """serve.tpot_s folds block_dt / tokens_emitted once PER EMITTED
    token (not one block_dt per dispatch): at H=4 the per-token
    percentiles must sit near a quarter of the block cost, not at it —
    pinned by count (one observation per token) and by sum ~= total
    decode wall time regardless of horizon."""
    from nezha_tpu import obs

    model, variables = model_and_vars
    obs.start_run(str(tmp_path / "tpot"), meta={"kind": "serve_test"})
    try:
        eng = Engine(model, variables,
                     dataclasses.replace(SCFG, max_batch_size=1,
                                         decode_horizon=4))
        sched = Scheduler(eng)
        rid = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=8))
        _drain(sched)
        h = obs.histogram("serve.tpot_s")
        assert h.count == 8            # one observation per token...
        assert eng.step_calls == 2     # ...from only two dispatches
        # Each block contributes e * (dt / e) = dt to the sum, so the
        # mean tpot is (total decode time) / tokens — the number that
        # stays comparable across horizon settings.
        s = h.summary()
        assert s["p50"] <= s["sum"] / 2     # not one whole block per tok
        tt = obs.histogram("serve.ttft_s")
        assert tt.count == 1
        # TTFT used the first token's position within the first block:
        # strictly less than the full block would have charged.
        assert sched.results[rid].ttft_s < sched.results[rid].latency_s
    finally:
        obs.end_run()


def test_serving_benchmark_cli(tmp_path):
    """benchmarks/serving.py drives the stack end to end and writes
    schema-valid artifacts (the load-vs-latency record of the ISSUE)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    import serving as bench

    run_dir = str(tmp_path / "bench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--requests", "6", "--concurrency", "2", "--prompt-len", "4",
         "--max-new-tokens", "4", "--max-batch-size", "2",
         "--max-len", "16", "--max-prefill-len", "8",
         "--run-dir", run_dir]))
    assert rec["finished"] == 6 and rec["tokens"] == 24
    assert rec["compile_cache"]["misses"] == 2
    assert rec["ttft_s"]["p50"] > 0 and rec["tokens_per_sec"] > 0
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []


def test_nezha_serve_stdio_jsonl():
    """The nezha-serve stdio front end: JSONL requests in (including a
    bad line), streamed token + done events out, byte-level text."""
    import io

    from nezha_tpu.cli.serve import build_parser, run as serve_run

    lines = "\n".join([
        json.dumps({"id": "a", "prompt_tokens": [5, 17, 3, 42],
                    "max_new_tokens": 5}),
        json.dumps({"id": "b", "prompt": "hi", "max_new_tokens": 3,
                    "temperature": 1.0, "top_k": 9, "seed": 4}),
        "garbage line",
        json.dumps({"id": "c", "prompt_tokens": [999]}),  # out of vocab
    ]) + "\n"
    stdout = io.StringIO()
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "32", "--max-prefill-len", "8",
         "--platform", "cpu"])
    assert serve_run(args, stdin=io.StringIO(lines), stdout=stdout) == 0
    events = [json.loads(ln) for ln in stdout.getvalue().splitlines()]
    done = {e["id"]: e for e in events if e["event"] == "done"}
    errors = [e for e in events if e["event"] == "error"]
    assert len(done["a"]["tokens"]) == 5
    assert done["a"]["finish_reason"] == "length"
    assert len(done["b"]["tokens"]) == 3
    assert isinstance(done["b"]["text"], str)
    assert len(errors) == 2
    # token events streamed before each done, tagged per request
    a_tokens = [e["token"] for e in events
                if e["event"] == "token" and e["id"] == "a"]
    assert a_tokens == done["a"]["tokens"]
