"""Distributed request tracing + the live /stats fleet view (ISSUE 12).

Layers under test, bottom up: trace minting/sampling and span adoption
(obs.registry — unit coverage lives in test_obs.py), the scheduler's
per-request lifecycle fragments (queue wait / prefill / park / export /
decode windows / retire), the router's mint-and-forward propagation
across a DISAGGREGATED 1-prefill + 1-decode fleet (the acceptance: every
completed request stitches into a complete timeline whose segment sum
tiles its TTFT exactly, no orphan fragments), partial/orphan-trace
rendering (a killed replica's surviving fragments must render, not
crash), the zero-overhead pins (telemetry disabled, or sampled out,
adds ZERO spans), and the ``GET /stats`` payloads — replica and fleet —
held to the pinned stats schema mid-load.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import pytest

import jax

from nezha_tpu import faults, obs
from nezha_tpu.obs.report import (TRACE_SEGMENTS, render_trace_report,
                                  stitch_run_dir, trace_summary)
from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig
from nezha_tpu.serve.router import Router, register_router_instruments
from nezha_tpu.serve.scheduler import register_serve_instruments
from nezha_tpu.serve.supervisor import (RouterConfig, Supervisor,
                                        ThreadBackend)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
from check_telemetry_schema import check_run_dir, check_stats_payload  # noqa: E402

# The per-request lifecycle fragments a clean disaggregated migration
# leaves behind, per trace (decode_window is per-dispatch; at least one).
_DISAGG_LIFECYCLE = {"router.request", "serve.queue_wait",
                     "serve.prefill", "serve.park", "serve.kv_export",
                     "serve.kv_install", "serve.decode_window",
                     "serve.decode"}


@pytest.fixture(autouse=True)
def _clean_obs():
    faults.clear()
    obs.end_run()
    obs.REGISTRY.reset()
    obs.set_trace_sample(1.0)
    yield
    faults.clear()
    obs.end_run()
    obs.REGISTRY.reset()
    obs.set_trace_sample(1.0)


@pytest.fixture(scope="module")
def tiny_model():
    from nezha_tpu.cli.train import TINY_GPT2_KW
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config
    model = GPT2(GPT2Config(**TINY_GPT2_KW))
    return model, model.init(jax.random.PRNGKey(0))


def _engine(tiny_model, **kw):
    model, variables = tiny_model
    base = dict(max_batch_size=2, max_len=64, max_prefill_len=16,
                kv_block_size=8, queue_capacity=8)
    base.update(kw)
    return Engine(model, variables, ServeConfig(**base))


def _prompt(n, vocab=512, salt=0):
    return [(7 * i + 3 + 11 * salt) % vocab for i in range(n)]


def _assert_tiles(timeline):
    """The tiling invariant: a complete timeline's segments sum to its
    TTFT exactly — no hidden gap between consecutive milestones."""
    assert timeline["complete"], timeline
    assert set(timeline["segments"]) == set(TRACE_SEGMENTS)
    assert all(v >= 0.0 for v in timeline["segments"].values()), timeline
    assert (sum(timeline["segments"].values())
            == pytest.approx(timeline["ttft_s"], abs=1e-9))


# ------------------------------------------------------- single replica
def test_single_replica_stitched_timelines(tiny_model, tmp_path):
    """A router-less scheduler is its own admission edge: with a run
    active it mints per-request trace ids at submit, and every request
    stitches into a complete timeline whose segment sum matches the
    scheduler-measured TTFT."""
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir, meta={"kind": "serve"})
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    for i in range(4):
        sched.submit(Request(prompt=_prompt(5 + 9 * i, salt=i),
                             max_new_tokens=4, request_id=f"t{i}"))
    sched.run_until_idle()
    results = dict(sched.results)
    obs.end_run()

    timelines = {t["request_id"]: t for t in stitch_run_dir(run_dir)}
    assert sorted(timelines) == ["t0", "t1", "t2", "t3"]
    for rid, t in timelines.items():
        _assert_tiles(t)
        assert t["migrated"] is False
        assert t["segments"]["migration_transfer"] == 0.0
        # The stitched TTFT (wall clock, admission edge -> first token)
        # agrees with the scheduler's own measurement (monotonic clock,
        # submit -> first token): same interval, two clocks.
        assert t["ttft_s"] == pytest.approx(results[rid].ttft_s,
                                            abs=0.25)
        assert {"serve.queue_wait", "serve.prefill",
                "serve.prefill.chunk", "serve.decode_window",
                "serve.decode"} <= set(t["span_names"])
    # The capture (trace fields + new span names included) stays
    # schema-valid.
    assert check_run_dir(run_dir) == []
    report = render_trace_report(run_dir)
    assert "4 complete, 0 partial" in report
    assert "prefill_compute" in report and "critical path" in report
    summary = trace_summary(run_dir)
    assert summary["count"] == 4 and summary["complete"] == 4
    assert set(summary["segments"]) == set(TRACE_SEGMENTS)


def test_trace_chain_parents_nest(tiny_model, tmp_path):
    """Fragment lineage: serve.prefill.chunk spans are children of the
    serve.prefill span (parent_id chains), and every fragment of one
    request shares one trace_id."""
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    # 21 tokens -> 2 chunks through the 16-wide prefill (16 + tail)
    sched.submit(Request(prompt=_prompt(21), max_new_tokens=2,
                         request_id="chain"))
    sched.run_until_idle()
    obs.end_run()
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    traced = [s for s in spans if s.get("trace_id")]
    tids = {s["trace_id"] for s in traced}
    assert len(tids) == 1
    prefill = [s for s in traced if s["name"] == "serve.prefill"]
    chunks = [s for s in traced if s["name"] == "serve.prefill.chunk"]
    assert len(prefill) == 1 and len(chunks) == 2
    assert all(c["parent_id"] == prefill[0]["span_id"] for c in chunks)


# ------------------------------------------------------ zero-span pins
def test_telemetry_disabled_serving_adds_zero_spans(tiny_model):
    """The branch-only no-op pin at the serving layer: with no run
    active a full serve cycle records NOTHING — no spans, no trace ids
    minted, no per-request state retained."""
    assert not obs.enabled()
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    rid = sched.submit(Request(prompt=_prompt(9), max_new_tokens=3))
    sched.run_until_idle()
    assert sched.results[rid].finish_reason == "length"
    assert obs.REGISTRY.spans == []
    assert obs.mint_trace_id() is None
    assert obs.span("serve.drain") is obs.NULL_SPAN
    assert obs.traced_span("serve.decode") is obs.NULL_SPAN


def test_trace_sampled_out_adds_zero_trace_spans(tiny_model, tmp_path):
    """--trace-sample 0: the run still captures the classic spans
    (serve.prefill, serve.decode_attention) but NOT ONE per-request
    trace fragment — tracing cost scales with the sample knob."""
    run_dir = str(tmp_path / "run")
    obs.set_trace_sample(0.0)
    obs.start_run(run_dir)
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sched.submit(Request(prompt=_prompt(9), max_new_tokens=3,
                         request_id="s0"))
    sched.run_until_idle()
    obs.end_run()
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    names = {s["name"] for s in spans}
    assert "serve.prefill" in names and "serve.decode_attention" in names
    assert not any(s.get("trace_id") for s in spans)
    assert not names & {"serve.queue_wait", "serve.decode",
                        "serve.decode_window", "serve.prefill.chunk"}
    assert stitch_run_dir(run_dir) == []
    assert trace_summary(run_dir) is None
    assert "no trace fragments" in render_trace_report(run_dir)


def test_router_sampled_out_marker_is_honored(tiny_model, tmp_path):
    """The router is the fleet's SINGLE sampling edge: a routed request
    the router sampled out arrives with trace_id == "" and the replica
    scheduler must honor the verdict — no re-mint, zero trace
    fragments — else --trace-sample P would really trace ~P+(1-P)P of
    traffic with root-less timelines."""
    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sched.submit(Request(prompt=_prompt(9), max_new_tokens=2,
                         request_id="routed-out", trace_id=""))
    sched.run_until_idle()
    obs.end_run()
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    assert not any(s.get("trace_id") for s in spans)
    assert stitch_run_dir(run_dir) == []
    # the wire parser keeps "" distinct from absent
    from nezha_tpu.cli.serve import _parse_request, build_parser
    args = build_parser().parse_args(["--random-init"])
    req = _parse_request({"prompt_tokens": [1, 2], "trace_id": ""},
                         args, None, None, 512)
    assert req.trace_id == ""
    req = _parse_request({"prompt_tokens": [1, 2]}, args, None, None,
                         512)
    assert req.trace_id is None


def test_router_scrubs_malformed_client_trace_id(tmp_path):
    """A client-supplied non-string trace_id must neither poison the
    span schema nor crash the forward path: the router scrubs it and
    mints its own."""
    from nezha_tpu.serve.supervisor import Supervisor

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir)
    register_router_instruments()
    cfg = RouterConfig(replicas=1, probe_timeout_s=0.5)

    class _NoSpawnBackend:
        kind = "stub"

        def spawn(self, rid, port):
            raise RuntimeError("never spawned")

    sup = Supervisor(_NoSpawnBackend(), cfg)   # no replicas started
    router = Router(sup, cfg)
    for bad in (123, {"x": 1}, ["y"], None):
        status, obj = router.route(
            {"id": "bad", "prompt_tokens": [1], "trace_id": bad})
        assert status == 503 and obj["error_type"] == "no_live_replicas"
    obs.end_run()
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    roots = [s for s in spans if s["name"] == "router.request"]
    assert len(roots) == 4
    for s in roots:
        assert isinstance(s["trace_id"], str) and s["trace_id"]
    assert check_run_dir(run_dir) == []


# -------------------------------------------------- disaggregated fleet
def _worker_args(extra=()):
    from nezha_tpu.cli.serve import build_parser
    return build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "64", "--max-prefill-len", "8",
         "--kv-block-size", "8", "--queue-capacity", "8",
         "--platform", "cpu", *extra])


def _cfg(**kw):
    base = dict(replicas=2, roles=("prefill", "decode"),
                probe_interval_s=0.1, probe_misses=3, route_retries=2,
                retry_backoff_base_s=0.01, retry_backoff_max_s=0.05,
                restart_backoff_base_s=0.05, restart_backoff_max_s=0.5,
                drain_timeout_s=20.0, seed=0)
    base.update(kw)
    return RouterConfig(**base)


def _cluster(cfg):
    sup = Supervisor(ThreadBackend(_worker_args(), drain_timeout_s=20.0,
                                   roles=cfg.roles), cfg)
    router = Router(sup, cfg)
    sup.start()
    assert router.wait_live(cfg.replicas, timeout_s=600), sup.describe()
    return sup, router


def test_disaggregated_fleet_stitch_acceptance(tiny_model, tmp_path):
    """THE acceptance run: 1 prefill + 1 decode replicas with
    migration, concurrent traced load. Every completed request stitches
    into a COMPLETE timeline covering every lifecycle segment (park,
    export, install, both queue waits), with zero orphan fragments; the
    segment sum tiles the stitched TTFT exactly and brackets the
    independently measured latencies; and GET /stats (replica + fleet)
    answers schema-valid payloads MID-LOAD."""
    cfg = _cfg()
    sup, router = _cluster(cfg)
    run_dir = str(tmp_path / "fleet")
    obs.start_run(run_dir, meta={"kind": "tracing_acceptance"})
    register_router_instruments()
    register_serve_instruments()
    N = 6
    results = {}
    lock = threading.Lock()
    next_idx = {"n": 0}
    stats_payloads = []
    try:
        def client():
            while True:
                with lock:
                    i = next_idx["n"]
                    if i >= N:
                        return
                    next_idx["n"] += 1
                t_req = time.monotonic()
                code, obj = router.route(
                    {"id": f"tr-{i}", "prompt_tokens": _prompt(21, salt=i),
                     "max_new_tokens": 4, "seed": i})
                with lock:
                    results[f"tr-{i}"] = (code, obj,
                                          time.monotonic() - t_req)

        threads = [threading.Thread(target=client) for _ in range(2)]
        for t in threads:
            t.start()
        # Mid-load live view: the fleet snapshot (what the router's
        # GET /stats answers) and one replica's own /stats over real
        # HTTP, both while requests are in flight.
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            with lock:
                if results:
                    break
            time.sleep(0.005)
        stats_payloads.append(router.fleet_stats())
        port = sup.replicas()[0].port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=30) as resp:
            stats_payloads.append(json.loads(resp.read()))
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads)
    finally:
        obs.end_run()
        router.stop()
        sup.shutdown()

    assert len(results) == N
    assert all(code == 200 for code, _, _ in results.values()), results

    # ---- live /stats: schema-valid mid-load, fleet roll-up present
    fleet, replica = stats_payloads
    assert check_stats_payload(fleet) == []
    assert check_stats_payload(replica) == []
    assert fleet["kind"] == "fleet" and fleet["enabled"] is True
    assert len(fleet["replicas"]) == 2
    assert {r["role"] for r in fleet["replicas"]} == {"prefill",
                                                      "decode"}
    # thread-backed replicas share the process registry, so the serve
    # instruments are visible in every payload
    assert "serve.admitted_total" in fleet["fleet"]["counters"]
    assert "serve.admitted_total" in replica["counters"]
    assert replica["role"] in ("prefill", "decode")

    # ---- stitched timelines: complete, tiled, no orphans
    timelines = {t["request_id"]: t for t in stitch_run_dir(run_dir)}
    assert sorted(timelines) == sorted(results)
    for rid, t in timelines.items():
        _assert_tiles(t)
        assert t["migrated"] is True
        assert t["segments"]["migration_transfer"] > 0.0
        assert _DISAGG_LIFECYCLE <= set(t["span_names"]), t
        code, obj, wall = results[rid]
        # The stitched end-to-end TTFT brackets the independent
        # measurements: at least the decode replica's own TTFT
        # (a strict component of it), at most the whole measured
        # route round trip.
        assert t["ttft_s"] >= obj["ttft_s"] - 0.05, (t, obj)
        assert t["ttft_s"] <= wall + 0.05, (t, wall)
        assert t["finish_reason"] == "length"
    # No orphan fragments: every traced span record stitched into a
    # COMPLETE timeline (partial count 0).
    summary = trace_summary(run_dir)
    assert summary["count"] == N
    assert summary["complete"] == N and summary["partial"] == 0
    assert summary["segments"]["migration_transfer"]["p50"] > 0

    # ---- the capture stays schema-valid end to end
    assert check_run_dir(run_dir) == []
    report = render_trace_report(run_dir)
    assert f"{N} complete, 0 partial" in report
    assert "migration_transfer" in report


def test_partial_and_orphan_trace_rendering(tiny_model, tmp_path):
    """A request whose lifecycle was cut short (parked, puller killed
    before decoding — the drain sweeps the park) must surface as a
    PARTIAL trace, and a lone surviving fragment from a killed
    replica's run dir as an orphan — both rendered, never crashing the
    stitcher, never counted complete."""
    run_dir = str(tmp_path / "partial")
    obs.start_run(run_dir)
    eng = _engine(tiny_model)
    sched = Scheduler(eng)
    sched.submit(Request(prompt=_prompt(21), max_new_tokens=4,
                         request_id="cut", prefill_only=True))
    sched.run_until_idle()
    assert sched.results["cut"].finish_reason == "prefilled"
    assert sched.parked_count == 1
    sched.cancel_remaining()            # the drain sweep: park released
    obs.end_run()

    # A killed decode replica's only surviving fragment, in its own
    # per-replica subdir (the layout a --replicas run-dir writes).
    orphan_dir = os.path.join(run_dir, "replica9")
    os.makedirs(orphan_dir)
    with open(os.path.join(orphan_dir, "spans.jsonl"), "w") as f:
        f.write(json.dumps({
            "name": "serve.kv_install", "t0": 1.0, "t1": 2.0,
            "dur_s": 1.0, "attrs": {"request_id": "ghost"},
            "trace_id": "feedfacefeedface",
            "span_id": "0123456789abcdef"}) + "\n")

    timelines = stitch_run_dir(run_dir)
    assert len(timelines) == 2
    by_rid = {t["request_id"]: t for t in timelines}
    cut = by_rid["cut"]
    assert not cut["complete"]
    assert "serve.park" in cut["span_names"]      # outcome fragment
    assert "serve.decode" in cut["missing"] or \
        "first token" in cut["missing"]
    ghost = by_rid["ghost"]
    assert not ghost["complete"]
    assert ghost["fragments"] == 1
    assert ghost["replicas"] == ["replica9"]
    report = render_trace_report(run_dir)
    assert "partial traces (2" in report
    assert "cut" in report and "ghost" in report
    # the park resolution is recorded
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        parks = [json.loads(ln) for ln in f
                 if ln.strip() and "serve.park" in ln]
    assert parks and parks[-1]["attrs"]["outcome"] == "drained"
    assert check_run_dir(run_dir) == []


def test_failed_install_does_not_count_as_migration():
    """A ``serve.kv_install`` fragment recorded with an ``error`` attr
    (the pull failed; the router degraded to a retry or local decode)
    must not flip the timeline to migrated=true with a positive
    transfer segment — that would mask exactly the degradation the
    trace report exists to surface. A clean retry fragment alongside
    the failed one still counts."""
    from nezha_tpu.obs.report import trace_timeline

    def frag(name, t0, t1, **attrs):
        return {"name": name, "t0": t0, "t1": t1, "dur_s": t1 - t0,
                "attrs": {"request_id": "r", **attrs}, "_src": "."}

    base = [
        frag("router.request", 0.0, 3.0),
        frag("serve.queue_wait", 0.1, 0.2),
        frag("serve.prefill", 0.2, 1.0),
        frag("serve.decode", 1.8, 3.0, first_token=2.0,
             finish_reason="length"),
    ]
    failed = frag("serve.kv_install", 1.0, 1.5, error="MigrationError")
    t = trace_timeline("a" * 16, base + [failed])
    assert t["complete"], t
    assert t["migrated"] is False
    assert t["segments"]["migration_transfer"] == 0.0
    ok = frag("serve.kv_install", 1.0, 1.6)
    t2 = trace_timeline("a" * 16, base + [failed, ok])
    assert t2["migrated"] is True
    assert t2["segments"]["migration_transfer"] == pytest.approx(0.6)


def test_trace_propagates_per_request_not_per_park_ttl(tiny_model,
                                                      tmp_path):
    """Scheduler-level migration lifecycle: park -> export -> install
    -> ack across two engines stitches export and install fragments
    into ONE trace (the pull reference carries the id), and the park
    span resolves 'acked'."""
    from nezha_tpu.serve import migrate
    run_dir = str(tmp_path / "mig")
    obs.start_run(run_dir)
    a, b = _engine(tiny_model), _engine(tiny_model)
    sa, sb = Scheduler(a), Scheduler(b)
    prompt = _prompt(21)
    tid = "aaaabbbbccccdddd"
    sa.submit(Request(prompt=prompt, max_new_tokens=4, request_id="m",
                      prefill_only=True, trace_id=tid))
    sa.run_until_idle()
    with obs.trace_context(None):       # no ambient leakage either way
        tokens, layers, nbytes = migrate.decode_wire(
            sa.export_parked("m"))
    with obs.trace_context(tid):
        sb.install_migrated(tokens, layers, nbytes)
    assert sa.ack_parked("m") is True
    obs.end_run()
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        spans = [json.loads(ln) for ln in f if ln.strip()]
    export = [s for s in spans if s["name"] == "serve.kv_export"]
    parks = [s for s in spans if s["name"] == "serve.park"]
    assert export and export[0]["trace_id"] == tid
    assert export[0]["attrs"]["bytes"] > 0
    assert parks and parks[0]["trace_id"] == tid
    assert parks[0]["attrs"]["outcome"] == "acked"
    a.pool.leak_check()
    b.pool.leak_check()


# ----------------------------------------------------- CLI front ends
def test_cli_front_end_stats_and_trace(tmp_path):
    """nezha-serve --replicas 2 end to end: GET /stats on the router
    answers the schema-valid fleet payload over real HTTP, a traced
    POST /generate tagged via the X-Nezha-Trace header at the FLEET
    entry point (the RUNBOOK repro workflow) leaves a stitchable
    complete timeline under the operator's id in the run dir, and
    nezha-telemetry --trace renders it."""
    from nezha_tpu.cli.serve import build_parser, run

    run_dir = str(tmp_path / "router_run")
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8", "--platform",
         "cpu", "--replicas", "2", "--replica-backend", "thread",
         "--http", "0", "--probe-interval", "0.1", "--drain-timeout",
         "20", "--run-dir", run_dir])
    ready, rc = {}, {}
    ready_evt, drain = threading.Event(), threading.Event()

    def ready_cb(server):
        ready["port"] = server.server_address[1]
        ready_evt.set()

    t = threading.Thread(
        target=lambda: rc.update(rc=run(args, ready_cb=ready_cb,
                                        drain_event=drain)),
        daemon=True)
    t.start()
    assert ready_evt.wait(timeout=300)
    base = f"http://127.0.0.1:{ready['port']}"
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=5) as r:
                if json.loads(r.read())["replicas_live"] == 2:
                    break
        except Exception:
            pass
        time.sleep(0.1)
    tid = "beadbeadbeadbead"
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"id": "cli-trace", "prompt_tokens": [5, 17, 3],
                         "max_new_tokens": 4}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Nezha-Trace": tid})
    with urllib.request.urlopen(req, timeout=120) as r:
        obj = json.loads(r.read())
    assert obj["finish_reason"] == "length"
    with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
        fleet = json.loads(r.read())
    assert check_stats_payload(fleet) == []
    assert fleet["kind"] == "fleet" and len(fleet["replicas"]) == 2
    drain.set()
    t.join(timeout=300)
    assert not t.is_alive() and rc.get("rc") == 0

    timelines = {t_["request_id"]: t_
                 for t_ in stitch_run_dir(run_dir)}
    assert "cli-trace" in timelines
    _assert_tiles(timelines["cli-trace"])
    # The router honored the header: the timeline stitches under the
    # operator-supplied id, not a router-minted one.
    assert timelines["cli-trace"]["trace_id"] == tid
    from nezha_tpu.cli.telemetry import main as telemetry_main
    assert telemetry_main([run_dir, "--trace"]) == 0


def test_worker_stats_endpoint_and_trace_header(tiny_model, tmp_path):
    """The single-replica HTTP front end (cli/serve.run_http): GET
    /stats answers the replica stats payload, and a request whose
    trace rides ONLY in the X-Nezha-Trace header (no payload field)
    still stitches under that id."""
    from nezha_tpu.cli.serve import build_parser, run_worker

    run_dir = str(tmp_path / "worker")
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8",
         "--platform", "cpu", "--http", "0", "--drain-timeout", "10",
         "--run-dir", run_dir])
    ready, rc = {}, {}
    ready_evt, drain = threading.Event(), threading.Event()

    def ready_cb(server):
        ready["port"] = server.server_address[1]
        ready_evt.set()

    t = threading.Thread(
        target=lambda: rc.update(rc=run_worker(args, ready_cb=ready_cb,
                                               drain_event=drain)),
        daemon=True)
    t.start()
    assert ready_evt.wait(timeout=600)
    base = f"http://127.0.0.1:{ready['port']}"
    tid = "cafecafecafecafe"
    req = urllib.request.Request(
        f"{base}/generate",
        data=json.dumps({"id": "hdr", "prompt_tokens": [5, 17, 3],
                         "max_new_tokens": 3}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Nezha-Trace": tid})
    with urllib.request.urlopen(req, timeout=600) as r:
        assert json.loads(r.read())["finish_reason"] == "length"
    with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
        stats = json.loads(r.read())
    assert check_stats_payload(stats) == []
    assert stats["kind"] == "replica" and stats["enabled"] is True
    assert stats["counters"].get("serve.admitted_total") == 1
    drain.set()
    t.join(timeout=300)
    assert not t.is_alive() and rc.get("rc") == 0
    timelines = stitch_run_dir(run_dir)
    assert [t_["trace_id"] for t_ in timelines] == [tid]
    _assert_tiles(timelines[0])


# ----------------------------------------------------------- benchmark
def test_bench_record_trace_block(tmp_path):
    """benchmarks/serving.py --run-dir: the record's ``trace`` block
    carries per-segment p50/p90/p99 over the stitched timelines —
    the numbers nezha-bench's TTFT-decomposition gate compares."""
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import serving as bench

    run_dir = str(tmp_path / "bench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--requests", "4", "--concurrency", "2", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8",
         "--max-new-tokens", "3", "--run-dir", run_dir]))
    tr = rec["trace"]
    assert tr is not None
    assert tr["count"] == 4 and tr["complete"] == 4
    assert set(tr["segments"]) == set(TRACE_SEGMENTS)
    for seg in TRACE_SEGMENTS:
        assert {"p50", "p90", "p99"} <= set(tr["segments"][seg])
    assert tr["ttft_s"]["p50"] > 0
    # The nezha-bench gate helper reads exactly these keys.
    from nezha_tpu.cli.bench import _serving_trace_p50s
    p50s = _serving_trace_p50s({"closed_loop_horizon_sweep": rec})
    assert "trace.prefill_compute_p50@h1" in p50s
    assert check_run_dir(run_dir) == []


def test_bench_trace_gate_floor():
    """The TTFT-decomposition gate's noise floor: a segment whose
    BASELINE p50 is sub-millisecond gates nothing (CPU scheduler
    jitter moves microsecond waits past any sane threshold — the gate
    would flap), while a >=1ms segment gates normally in both
    directions."""
    from nezha_tpu.cli.bench import _gate

    def rec(p50s):
        return {"closed_loop_horizon_sweep": {"by_horizon": {"1": {
            "tokens_per_sec": 100.0,
            "trace": {"segments": {
                seg: {"p50": v} for seg, v in p50s.items()}}}}}}

    base = {"serving": {"by_platform": {"cpu": rec(
        {"prefill_compute": 0.010, "decode_wait": 0.0004})}}}
    ok = _gate({"serving": rec({"prefill_compute": 0.011,
                                "decode_wait": 0.4})},
               base, "cpu", 0.30)["serving"]
    # 1000x regression on the 0.4ms-baseline segment: not gated.
    assert "trace.decode_wait_p50@h1" not in ok
    assert ok["trace.prefill_compute_p50@h1"]["ok"] is True
    bad = _gate({"serving": rec({"prefill_compute": 0.020,
                                 "decode_wait": 0.0004})},
                base, "cpu", 0.30)["serving"]
    assert bad["trace.prefill_compute_p50@h1"]["ok"] is False
