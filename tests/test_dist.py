"""Native coordinator tests — rendezvous, KV, barrier, broadcast,
all-gather, failure detection (SURVEY.md §4: "test the coordinator with
in-process ranks"). Clients run on threads; blocking calls are in C and
release the GIL, so threads faithfully model separate ranks."""

import threading
import time

import pytest

from nezha_tpu.runtime.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native runtime library not buildable")

from nezha_tpu import dist  # noqa: E402


def _run_ranks(world, fn, **coord_kwargs):
    """Start a coordinator, join `world` clients on threads, run fn(group)
    on each, return rank-indexed results."""
    with dist.Coordinator(world_size=world, **coord_kwargs) as coord:
        results = [None] * world
        errors = []
        # Rank slots freed by leave() are reusable (elastic restart), so no
        # rank may leave until every rank has joined and finished.
        done = threading.Barrier(world)

        def worker(i):
            try:
                with dist.join("127.0.0.1", coord.port) as g:
                    results[g.rank] = fn(g)
                    done.wait(timeout=30)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        return results


def test_rendezvous_assigns_unique_ranks():
    ranks = _run_ranks(4, lambda g: (g.rank, g.world_size))
    assert sorted(r for r, _ in ranks) == [0, 1, 2, 3]
    assert all(w == 4 for _, w in ranks)


def test_rank_hint_honored():
    with dist.Coordinator(world_size=2) as coord:
        g1 = dist.join("127.0.0.1", coord.port, rank_hint=1)
        assert g1.rank == 1
        g0 = dist.join("127.0.0.1", coord.port)
        assert g0.rank == 0
        g0.leave()
        g1.leave()


def test_kv_put_get_blocking():
    def fn(g):
        if g.rank == 0:
            time.sleep(0.1)  # make rank 1 actually block on get
            g.put("topo", b"mesh:2x2")
        return g.get("topo", timeout_s=10)

    assert _run_ranks(2, fn) == [b"mesh:2x2"] * 2


def test_get_timeout_raises():
    with dist.Coordinator(world_size=1) as coord:
        with dist.join("127.0.0.1", coord.port) as g:
            with pytest.raises(dist.coordinator.CoordinatorError):
                g.get("never-put", timeout_s=0.2)


def test_large_value_roundtrip():
    blob = bytes(range(256)) * 1024  # 256 KiB > initial 64 KiB buffer

    def fn(g):
        if g.rank == 0:
            g.put("big", blob)
        return g.get("big", timeout_s=10)

    assert _run_ranks(2, fn) == [blob] * 2


def test_barrier_synchronizes():
    order = []
    lock = threading.Lock()

    def fn(g):
        # Stagger arrivals; nobody may pass until all have arrived.
        time.sleep(0.05 * g.rank)
        with lock:
            order.append(("arrive", g.rank))
        g.barrier(timeout_s=10)
        with lock:
            order.append(("pass", g.rank))
        return True

    assert all(_run_ranks(3, fn))
    arrivals = [i for i, (ev, _) in enumerate(order) if ev == "arrive"]
    passes = [i for i, (ev, _) in enumerate(order) if ev == "pass"]
    assert max(arrivals) < min(passes)


def test_barrier_reusable_across_epochs():
    def fn(g):
        for _ in range(5):
            g.barrier(timeout_s=10)
        return True

    assert all(_run_ranks(4, fn))


def test_broadcast_and_all_gather():
    def fn(g):
        b = g.broadcast(b"root-data" if g.rank == 0 else None,
                        root=0, timeout_s=10)
        ag = g.all_gather(f"rank{g.rank}".encode(), timeout_s=10)
        return b, ag

    for b, ag in _run_ranks(3, fn):
        assert b == b"root-data"
        assert ag == [b"rank0", b"rank1", b"rank2"]


def test_failure_detection_on_drop():
    with dist.Coordinator(world_size=2,
                          heartbeat_timeout_s=0.5) as coord:
        g0 = dist.join("127.0.0.1", coord.port,
                       heartbeat_interval_s=0.1)
        g1 = dist.join("127.0.0.1", coord.port,
                       heartbeat_interval_s=0.1)
        assert g0.failed_ranks() == []
        g1.close()  # abrupt: no LEAVE
        deadline = time.time() + 5
        failed = []
        while time.time() < deadline:
            failed = g0.failed_ranks()
            if failed:
                break
            time.sleep(0.05)
        assert failed == [1]
        g0.leave()


def test_graceful_leave_is_not_failure():
    with dist.Coordinator(world_size=2,
                          heartbeat_timeout_s=0.5) as coord:
        g0 = dist.join("127.0.0.1", coord.port,
                       heartbeat_interval_s=0.1)
        g1 = dist.join("127.0.0.1", coord.port,
                       heartbeat_interval_s=0.1)
        g1.leave()
        time.sleep(1.0)  # well past the heartbeat timeout
        assert g0.failed_ranks() == []
        g0.leave()


def test_client_connects_before_coordinator_up():
    """Launch-skew tolerance: client retries until the server binds."""
    port_holder = {}
    result = {}

    def late_client():
        # Wait for the port, then join (connect itself also retries).
        while "port" not in port_holder:
            time.sleep(0.01)
        g = dist.join("127.0.0.1", port_holder["port"], timeout_s=10)
        result["rank"] = g.rank
        g.leave()

    t = threading.Thread(target=late_client)
    t.start()
    time.sleep(0.2)
    with dist.Coordinator(world_size=1) as coord:
        port_holder["port"] = coord.port
        t.join(timeout=10)
    assert result["rank"] == 0


def test_crashed_rank_can_rejoin():
    """Supervisor workflow: rank crashes, replacement process re-claims the
    same rank slot and clears the failure."""
    with dist.Coordinator(world_size=2, heartbeat_timeout_s=0.5) as coord:
        g0 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.1)
        g1 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.1)
        rank1 = g1.rank
        g1.close()  # crash
        deadline = time.time() + 5
        while time.time() < deadline and g0.failed_ranks() != [rank1]:
            time.sleep(0.05)
        assert g0.failed_ranks() == [rank1]
        g1b = dist.join("127.0.0.1", coord.port, rank_hint=rank1)
        assert g1b.rank == rank1
        assert g0.failed_ranks() == []
        g1b.leave()
        g0.leave()


def test_left_rank_slot_is_reusable():
    with dist.Coordinator(world_size=1) as coord:
        g = dist.join("127.0.0.1", coord.port)
        assert g.rank == 0
        g.leave()
        g2 = dist.join("127.0.0.1", coord.port)
        assert g2.rank == 0
        g2.leave()


def test_repeated_all_gather_rounds_fresh():
    """Round counters: a second all_gather with the default tag must return
    the second round's values, not stale KV entries."""
    def fn(g):
        r1 = g.all_gather(f"a{g.rank}".encode(), timeout_s=10)
        r2 = g.all_gather(f"b{g.rank}".encode(), timeout_s=10)
        return r1, r2

    for r1, r2 in _run_ranks(2, fn):
        assert r1 == [b"a0", b"a1"]
        assert r2 == [b"b0", b"b1"]


def test_blocking_wait_does_not_trip_failure_detector():
    """A rank parked in a long get() must not be reported failed even
    though its heartbeat thread is queued behind the blocking request."""
    with dist.Coordinator(world_size=2, heartbeat_timeout_s=0.6) as coord:
        g0 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.2)
        g1 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.2)
        got = {}

        def waiter():
            got["v"] = g1.get("slow-key", timeout_s=10)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(1.5)  # well past heartbeat_timeout while g1 blocks
        assert g0.failed_ranks() == []
        g0.put("slow-key", b"done")
        t.join(timeout=10)
        assert got["v"] == b"done"
        g1.leave()
        g0.leave()


def test_peer_death_during_barrier_is_detected():
    """A rank that dies while others wait in a barrier must be noticed by
    the failure detector (socket probe inside the blocking wait)."""
    with dist.Coordinator(world_size=2, heartbeat_timeout_s=0.5) as coord:
        g0 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.1)
        g1 = dist.join("127.0.0.1", coord.port, heartbeat_interval_s=0.1)
        err = {}

        def waiter():
            try:
                g0.barrier(timeout_s=5)
            except dist.coordinator.CoordinatorError as e:
                err["e"] = e

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.3)
        g1.close()  # dies mid-barrier
        deadline = time.time() + 5
        failed = []
        while time.time() < deadline:
            failed = g0.failed_ranks()
            if failed:
                break
            time.sleep(0.05)
        assert failed == [1]
        t.join(timeout=10)  # barrier times out; rank 0 survives to react
        assert "e" in err
        g0.leave()


def test_join_timeout_is_typed_and_counted():
    """Dialing a port nobody serves exhausts the retry envelope inside
    timeout_s and raises the typed JoinTimeout (still a
    CoordinatorError), counting every failed attempt."""
    import socket

    from nezha_tpu import obs

    with socket.socket() as s:   # grab-and-release: a dead port
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    obs.enable()
    try:
        before = obs.counter("dist.join_retries_total").value
        t0 = time.monotonic()
        with pytest.raises(dist.JoinTimeout):
            dist.join("127.0.0.1", dead_port, timeout_s=1.0,
                      attempt_timeout_s=0.2, backoff_base_s=0.02)
        assert time.monotonic() - t0 < 5.0        # bounded, not hung
        assert obs.counter("dist.join_retries_total").value > before
    finally:
        obs.disable()
    assert issubclass(dist.JoinTimeout, dist.CoordinatorError)


def test_join_retries_through_injected_dial_failure():
    """A fault-injected failure on the first dial attempt is absorbed by
    the backoff envelope: the second attempt lands and the group works."""
    from nezha_tpu import faults

    faults.install(faults.FaultPlan.parse("dist.join:error@1"))
    try:
        with dist.Coordinator(world_size=1) as coord:
            g = dist.join("127.0.0.1", coord.port, backoff_base_s=0.01)
            assert g.rank == 0
            g.put("k", b"v")
            assert g.get("k", timeout_s=5) == b"v"
            g.leave()
        assert faults.active().injected_counts == {"dist.join": 1}
    finally:
        faults.clear()


def test_heartbeat_loss_is_counted_event():
    """An abrupt peer death surfaces from failed_ranks() as a counted
    (dist.heartbeat_lost_total) span-recorded event, not an exception."""
    from nezha_tpu import obs

    obs.enable()
    try:
        before = obs.counter("dist.heartbeat_lost_total").value
        spans_before = len(obs.REGISTRY.spans)
        with dist.Coordinator(world_size=2,
                              heartbeat_timeout_s=0.5) as coord:
            g0 = dist.join("127.0.0.1", coord.port,
                           heartbeat_interval_s=0.1)
            g1 = dist.join("127.0.0.1", coord.port,
                           heartbeat_interval_s=0.1)
            g1.close()  # abrupt: no LEAVE
            deadline = time.time() + 5
            failed = []
            while time.time() < deadline and not failed:
                failed = g0.failed_ranks()
                time.sleep(0.05)
            assert failed == [1]
            g0.failed_ranks()   # repeat poll: same transition, no recount
            assert (obs.counter("dist.heartbeat_lost_total").value
                    == before + 1)
            failure_spans = [s for s in obs.REGISTRY.spans[spans_before:]
                             if s["name"] == "dist.failure"]
            assert len(failure_spans) == 1
            assert failure_spans[0]["attrs"]["failed"] == [1]
            g0.leave()
    finally:
        obs.disable()


def test_incr_is_atomic_across_ranks():
    def fn(g):
        return [g.incr("ctr") for _ in range(10)]

    vals = sum(_run_ranks(4, fn), [])
    assert sorted(vals) == list(range(40))


def test_rejoined_rank_resumes_collective_rounds():
    """After one broadcast round, a crashed rank's replacement must join
    round 1, not replay round 0's stale KV entry."""
    with dist.Coordinator(world_size=2) as coord:
        g0 = dist.join("127.0.0.1", coord.port)
        g1 = dist.join("127.0.0.1", coord.port)
        r0 = {}

        def round_one():
            r0["v"] = g0.broadcast(b"addr-v1", root=0, timeout_s=10)

        t = threading.Thread(target=round_one)
        t.start()
        assert g1.broadcast(None, root=0, timeout_s=10) == b"addr-v1"
        t.join(timeout=10)
        g1.close()  # crash after round 0
        g1b = dist.join("127.0.0.1", coord.port, rank_hint=1)

        def round_two():
            r0["v2"] = g0.broadcast(b"addr-v2", root=0, timeout_s=10)

        t = threading.Thread(target=round_two)
        t.start()
        got = g1b.broadcast(None, root=0, timeout_s=10)
        t.join(timeout=10)
        assert got == b"addr-v2", "replacement read a stale round"
        g1b.leave()
        g0.leave()


def test_initialize_jax_distributed_two_processes(tmp_path):
    """The full multi-host bootstrap: two real OS processes rendezvous
    through the native coordinator, rank 0 advertises the jax.distributed
    address via the KV store, both enter jax.distributed.initialize, and
    each sees the GLOBAL runtime (process_count 2, 2 CPU devices, disjoint
    local devices). This is the exact path `nezha-train --coordinator`
    takes on a pod (dist/launch.py)."""
    import json
    import socket
    import sys

    from conftest import run_worker_processes

    # Free-port probe for the jax coordination service. (Small TOCTOU
    # window before rank 0 re-binds it; the suite runs single-process, and
    # the finally below reaps workers if a bind conflict ever hangs them.)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        jax_port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(f"""
import json, sys
import jax
jax.config.update("jax_platforms", "cpu")  # before device init (conftest rule)
from nezha_tpu import dist
from nezha_tpu.dist.launch import initialize_jax_distributed

group = dist.join("127.0.0.1", int(sys.argv[1]))
initialize_jax_distributed(group, coord_port={jax_port}, timeout_s=60)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Cross-process data path: a psum over the 2-device global mesh (one
# device per process) — the XLA collective rides the distributed runtime.
mesh = Mesh(np.array(jax.devices()), ("dp",))
shard = jax.device_put(jnp.array([float(group.rank + 1)]),
                       jax.local_devices()[0])
arr = jax.make_array_from_single_device_arrays(
    (2,), NamedSharding(mesh, P("dp")), [shard])
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(arr)
psum_val = float(total.addressable_shards[0].data)

# The int8 wire collective (all_to_all + all_gather composition) must also
# ride the cross-process runtime — the multi-host path of
# --grad-allreduce int8.
from nezha_tpu.parallel._compat import shard_map
from nezha_tpu.parallel.quantized import _qar_mean

vec = jax.make_array_from_single_device_arrays(
    (2, 256), NamedSharding(mesh, P("dp")),
    [jax.device_put(jnp.full((1, 256), float(group.rank + 1)),
                    jax.local_devices()[0])])
q8 = jax.jit(shard_map(lambda v: _qar_mean(v[0], "dp", 128)[None],
                       mesh=mesh, in_specs=(P("dp"),),
                       out_specs=P("dp")))(vec)
q8_val = float(np.asarray(q8.addressable_shards[0].data).mean())

print(json.dumps({{
    "rank": group.rank,
    "process_count": jax.process_count(),
    "process_index": jax.process_index(),
    "global_devices": len(jax.devices()),
    "local_devices": len(jax.local_devices()),
    "psum": psum_val,
    "int8_mean": q8_val,
}}))
group.leave()
""")
    with dist.Coordinator(world_size=2) as coord:
        results = run_worker_processes(
            [[sys.executable, str(worker), str(coord.port)]
             for _ in range(2)], timeout=120)
    for rc, _, err in results:
        assert rc == 0, err[-2000:]
    recs = [json.loads(out.strip().splitlines()[-1]) for _, out, _ in results]
    assert {r["rank"] for r in recs} == {0, 1}
    for r in recs:
        assert r["process_count"] == 2
        assert r["global_devices"] == 2  # both hosts' devices visible
        assert r["local_devices"] == 1   # but only its own are local
        assert r["process_index"] == r["rank"]  # coordinator rank == jax id
        assert r["psum"] == 3.0  # 1 + 2 summed ACROSS processes
        # int8-wire mean of (1, 2) across processes, exact at these values.
        assert abs(r["int8_mean"] - 1.5) < 0.02, r["int8_mean"]
