"""Layer unit tests against numpy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import nn, ops
from nezha_tpu.tensor.policy import bf16_policy


def test_linear_matches_numpy():
    layer = nn.Linear(8, 4)
    v = layer.init(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    y, _ = layer.apply(v, jnp.asarray(x))
    expected = x @ np.asarray(v["params"]["w"]) + np.asarray(v["params"]["b"])
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5)


def test_linear_bf16_policy_keeps_master_params_f32():
    layer = nn.Linear(8, 4, policy=bf16_policy())
    v = layer.init(jax.random.PRNGKey(0))
    assert v["params"]["w"].dtype == jnp.float32
    y, _ = layer.apply(v, jnp.ones((2, 8)))
    assert y.dtype == jnp.bfloat16


def test_conv2d_shapes_and_stride():
    conv = nn.Conv2d(3, 16, 3, stride=2, padding="SAME")
    v = conv.init(jax.random.PRNGKey(0))
    y, _ = conv.apply(v, jnp.ones((2, 8, 8, 3)))
    assert y.shape == (2, 4, 4, 16)


def test_conv2d_matches_lax_direct():
    conv = nn.Conv2d(2, 3, 3, stride=1, padding="VALID", use_bias=False)
    v = conv.init(jax.random.PRNGKey(1))
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 5, 5, 2))
    y, _ = conv.apply(v, x)
    ref = jax.lax.conv_general_dilated(
        x, v["params"]["w"], (1, 1), "VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=1e-5)


def test_batchnorm_normalizes_and_updates_stats():
    bn = nn.BatchNorm(4, momentum=0.5)
    v = bn.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 2, 2, 4)) * 3 + 1
    y, new_state = bn.apply(v, x, training=True)
    np.testing.assert_allclose(np.asarray(jnp.mean(y, axis=(0, 1, 2))),
                               np.zeros(4), atol=1e-4)
    np.testing.assert_allclose(np.asarray(jnp.std(y, axis=(0, 1, 2))),
                               np.ones(4), atol=1e-3)
    assert not np.allclose(np.asarray(new_state["mean"]), 0.0)
    # Eval mode uses running stats and returns no update.
    v2 = {"params": v["params"], "state": new_state}
    _, s2 = bn.apply(v2, x, training=False)
    assert s2 == {}


def test_layernorm_zero_mean_unit_var():
    ln = nn.LayerNorm(16)
    v = ln.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16)) * 5 + 2
    y, _ = ln.apply(v, x)
    np.testing.assert_allclose(np.asarray(jnp.mean(y, axis=-1)), np.zeros(4),
                               atol=1e-4)


def test_embedding_lookup_and_attend():
    emb = nn.Embedding(10, 6)
    v = emb.init(jax.random.PRNGKey(0))
    ids = jnp.array([[1, 2], [3, 4]])
    y, _ = emb.apply(v, ids)
    assert y.shape == (2, 2, 6)
    logits = emb.attend(v, y)
    assert logits.shape == (2, 2, 10)


def test_dropout_train_vs_eval():
    d = nn.Dropout(0.5)
    v = d.init(jax.random.PRNGKey(0))
    x = jnp.ones((100, 100))
    y_eval, _ = d.apply(v, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_train, _ = d.apply(v, x, training=True, rng=jax.random.PRNGKey(1))
    frac_zero = float(jnp.mean(y_train == 0))
    assert 0.4 < frac_zero < 0.6
    # Inverted scaling keeps the expectation.
    assert abs(float(jnp.mean(y_train)) - 1.0) < 0.1


def test_sequential_and_pools():
    model = nn.Sequential([nn.Linear(4, 8), nn.Linear(8, 2)])
    v = model.init(jax.random.PRNGKey(0))
    y, _ = model.apply(v, jnp.ones((3, 4)))
    assert y.shape == (3, 2)
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    assert nn.max_pool(x, 2, 2).shape == (1, 2, 2, 1)
    assert nn.avg_pool(x, 2, 2).shape == (1, 2, 2, 1)
    assert nn.global_avg_pool(x).shape == (1, 1)


def test_softmax_and_losses():
    logits = jnp.array([[1.0, 2.0, 3.0], [1.0, 1.0, 1.0]])
    p = ops.softmax(logits)
    np.testing.assert_allclose(np.asarray(jnp.sum(p, -1)), [1.0, 1.0], rtol=1e-6)
    labels = jnp.array([2, 1])
    ce = ops.softmax_cross_entropy_with_integer_labels(logits, labels)
    onehot = jax.nn.one_hot(labels, 3)
    ce2 = ops.cross_entropy_with_logits(logits, onehot)
    np.testing.assert_allclose(float(ce), float(ce2), rtol=1e-6)
    # Row 0 argmax==2 (correct); row 1 ties -> argmax 0 != 1 (wrong).
    assert float(ops.accuracy(logits, labels)) == 0.5


def test_masked_ce_ignore_index():
    logits = jnp.zeros((2, 3, 5))
    labels = jnp.array([[1, -100, 2], [-100, -100, 0]])
    loss = ops.softmax_cross_entropy_with_integer_labels(
        logits, labels, ignore_index=-100)
    np.testing.assert_allclose(float(loss), np.log(5), rtol=1e-5)


def test_label_smoothing_matches_soft_target_ce():
    """label_smoothing=eps == CE against (1-eps)*one_hot + eps/V."""
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(4, 7), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 7, 4), jnp.int32)
    eps = 0.1
    smoothed = ops.softmax_cross_entropy_with_integer_labels(
        logits, labels, label_smoothing=eps)
    soft = (1 - eps) * jax.nn.one_hot(labels, 7) + eps / 7
    ref = ops.cross_entropy_with_logits(logits, soft)
    np.testing.assert_allclose(float(smoothed), float(ref), rtol=1e-6)
    # eps=0 is exactly the plain CE
    base = ops.softmax_cross_entropy_with_integer_labels(logits, labels)
    zero = ops.softmax_cross_entropy_with_integer_labels(
        logits, labels, label_smoothing=0.0)
    np.testing.assert_allclose(float(zero), float(base), rtol=0)


def test_causal_mask_and_attention():
    q = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 8, 16))
    out = ops.dot_product_attention(q, q, q, mask=ops.causal_mask(8, 8))
    assert out.shape == (2, 4, 8, 16)
    # First position can only attend to itself -> output == v[0].
    np.testing.assert_allclose(np.asarray(out[:, :, 0]),
                               np.asarray(q[:, :, 0]), rtol=1e-4)
