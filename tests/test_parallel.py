"""Multi-device tests on the 8-device virtual CPU mesh: collectives, DP
equivalence with single-device training, ZeRO-1 equivalence with plain DP.
(SURVEY.md §4: test collectives on multi-device CPU XLA.)"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from nezha_tpu import data, ops, optim, parallel
from nezha_tpu.models.mlp import MLP
from nezha_tpu.parallel._compat import shard_map
from nezha_tpu.train.loop import init_train_state, make_train_step


def _loss_fn(logits, batch):
    return ops.softmax_cross_entropy_with_integer_labels(logits, batch["label"])


def test_make_mesh_axes(devices8):
    mesh = parallel.make_mesh({"dp": 2, "tp": 4})
    assert parallel.local_mesh_axes(mesh) == {"dp": 2, "tp": 4}
    mesh2 = parallel.make_mesh({"dp": -1})
    assert parallel.local_mesh_axes(mesh2)["dp"] == len(jax.devices())


def test_collectives_roundtrip(devices8):
    mesh = parallel.make_mesh({"dp": 8})

    def f(x):
        s = parallel.all_reduce_sum(x, "dp")
        g = parallel.all_gather(x, "dp")
        rs = parallel.reduce_scatter(g, "dp")
        return s, g, rs

    x = jnp.arange(8.0)
    mapped = shard_map(f, mesh=mesh, in_specs=P("dp"),
                       out_specs=(P("dp"), P("dp"), P("dp")))
    s, g, rs = jax.jit(mapped)(x)
    np.testing.assert_allclose(np.asarray(s), np.full(8, 28.0))  # sum 0..7
    # Every rank gathered the full vector -> concat is 8 tiled copies.
    np.testing.assert_allclose(np.asarray(g), np.tile(np.arange(8.0), 8))
    # reduce_scatter of the replicated gather: each rank gets 8 * its element.
    np.testing.assert_allclose(np.asarray(rs), 8.0 * np.arange(8.0))


def test_ring_permute(devices8):
    mesh = parallel.make_mesh({"sp": 8})
    mapped = shard_map(lambda x: parallel.ring_permute(x, "sp"),
                       mesh=mesh, in_specs=P("sp"), out_specs=P("sp"))
    out = jax.jit(mapped)(jnp.arange(8.0))
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_barrier_runs(devices8):
    parallel.barrier(parallel.make_mesh({"dp": 8}))


def test_dp_matches_single_device(devices8):
    """DP over 8 devices must produce the same params as one big-batch step."""
    mesh = parallel.make_mesh({"dp": 8})
    model = MLP(hidden=(32,))
    opt = optim.sgd(0.1)

    state_single = init_train_state(model, opt, jax.random.PRNGKey(0))
    state_dp = jax.tree_util.tree_map(jnp.copy, state_single)
    state_dp = parallel.replicate(mesh, state_dp)

    batch = next(data.mnist_batches(64, seed=3))

    single_step = make_train_step(model, opt, _loss_fn, donate=False)
    dp_step = parallel.make_dp_train_step(model, opt, _loss_fn, mesh,
                                          donate=False)

    state_single, m1 = single_step(state_single, batch)
    state_dp, m2 = dp_step(state_dp, parallel.shard_batch(mesh, batch))

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state_single["variables"]),
                    jax.tree_util.tree_leaves(state_dp["variables"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=1e-5)


def test_zero1_matches_dp(devices8):
    """ZeRO-1 sharded optimizer must track plain DP step-for-step."""
    mesh = parallel.make_mesh({"dp": 8})
    model = MLP(hidden=(32,))
    opt = optim.adamw(1e-2, weight_decay=0.01)

    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    dp_state = parallel.replicate(mesh, jax.tree_util.tree_map(jnp.copy, state))

    z_vars = jax.tree_util.tree_map(jnp.copy, state["variables"])
    zero_state = {
        "variables": parallel.replicate(mesh, z_vars),
        "opt_state": parallel.zero1_init_opt_state(opt, z_vars["params"], mesh),
        "rng": parallel.replicate(mesh, state["rng"]),
    }

    dp_step = parallel.make_dp_train_step(model, opt, _loss_fn, mesh, donate=False)
    z_step = parallel.make_zero1_train_step(model, opt, _loss_fn, mesh, donate=False)

    batches = data.mnist_batches(64, seed=4)
    for _ in range(3):
        batch = parallel.shard_batch(mesh, next(batches))
        dp_state, m_dp = dp_step(dp_state, batch)
        zero_state, m_z = z_step(zero_state, batch)

    np.testing.assert_allclose(float(m_dp["loss"]), float(m_z["loss"]), rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(dp_state["variables"]["params"]),
                    jax.tree_util.tree_leaves(zero_state["variables"]["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_zero1_opt_state_is_sharded(devices8):
    mesh = parallel.make_mesh({"dp": 8})
    model = MLP(hidden=(32,))
    opt = optim.adamw(1e-2)
    variables = model.init(jax.random.PRNGKey(0))
    opt_state = parallel.zero1_init_opt_state(opt, variables["params"], mesh)
    mu_leaf = jax.tree_util.tree_leaves(opt_state["mu"])[0]
    # Each device holds 1/8th of the flat stat.
    shard_shapes = {s.data.shape for s in mu_leaf.addressable_shards}
    assert all(s[0] == mu_leaf.shape[0] // 8 for s in shard_shapes)


def test_ring_attention_matches_full(devices8):
    mesh = parallel.make_mesh({"sp": 8})
    b, h, s, d = 2, 4, 64, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))

    for causal in (True, False):
        out = parallel.ring_self_attention(mesh, q, k, v, causal=causal)
        mask = ops.causal_mask(s, s) if causal else None
        ref = ops.dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


def test_ulysses_matches_full(devices8):
    from nezha_tpu.parallel.sequence_parallel import ulysses_attention
    mesh = parallel.make_mesh({"sp": 8})
    b, h, s, d = 2, 8, 64, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))

    fn = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None))
    out = jax.jit(fn)(q, k, v)
    ref = ops.dot_product_attention(q, k, v, mask=ops.causal_mask(s, s))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_zero1_clip_uses_global_norm(devices8):
    """Gradient clipping under ZeRO-1 must clip by the GLOBAL norm (psum
    over the dp axis of the shard norms): with axis_name="dp" the zero1
    run matches the dp run step-for-step at a clip value that bites."""
    import jax

    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.loop import init_train_state

    mesh = parallel.make_mesh({"dp": 8})
    model = MLP(16, (32,), 4)
    ce = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"]).mean()
    r = np.random.RandomState(0)
    x = (r.randn(32, 16) * 5).astype(np.float32)  # big grads -> clip bites
    y = r.randint(0, 4, 32).astype(np.int32)
    b = parallel.shard_batch(mesh, {"image": jnp.asarray(x),
                                    "label": jnp.asarray(y)})

    def losses(make_opt, make_step, init_state):
        opt = make_opt()
        state = init_state(opt)
        step = make_step(opt)
        out = []
        for _ in range(3):
            state, m = step(state, b)
            out.append(float(m["loss"]))
        return out

    clip = 0.05  # well under the raw grad norm

    base = init_train_state(model, optim.sgd(0.5), jax.random.PRNGKey(0))

    dp_losses = losses(
        lambda: optim.with_grad_clipping(optim.sgd(0.5), clip),
        lambda opt: parallel.make_dp_train_step(model, opt, ce, mesh,
                                                donate=False),
        lambda opt: parallel.replicate(
            mesh, init_train_state(model, opt, jax.random.PRNGKey(0))))

    z_losses = losses(
        lambda: optim.with_grad_clipping(optim.sgd(0.5), clip,
                                         axis_name="dp"),
        lambda opt: parallel.make_zero1_train_step(model, opt, ce, mesh,
                                                   donate=False),
        lambda opt: {
            "variables": parallel.replicate(
                mesh, jax.tree_util.tree_map(jnp.copy, base["variables"])),
            "opt_state": parallel.zero1_init_opt_state(
                opt, base["variables"]["params"], mesh),
            "rng": parallel.replicate(mesh, jnp.copy(base["rng"])),
        })
    np.testing.assert_allclose(z_losses, dp_losses, rtol=1e-5)

    # Without the axis the shard-local norms under-clip: numerics diverge.
    z_bad = losses(
        lambda: optim.with_grad_clipping(optim.sgd(0.5), clip),
        lambda opt: parallel.make_zero1_train_step(model, opt, ce, mesh,
                                                   donate=False),
        lambda opt: {
            "variables": parallel.replicate(
                mesh, jax.tree_util.tree_map(jnp.copy, base["variables"])),
            "opt_state": parallel.zero1_init_opt_state(
                opt, base["variables"]["params"], mesh),
            "rng": parallel.replicate(mesh, jnp.copy(base["rng"])),
        })
    assert abs(z_bad[-1] - dp_losses[-1]) > 1e-4, (z_bad, dp_losses)
