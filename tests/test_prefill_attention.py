"""Paged flash-prefill kernel (ISSUE 18): the Pallas chunked-prefill
attention that reads K/V through the block table with per-row start
offsets, and — on int8 pools — fuses the block write (fresh per-(block,
head) scales, stale-position zeroing) into the kernel epilogue in place
of the ``_quant_prefill_write`` gather/requant round-trip.

Pins, per the acceptance list:

- kernel vs the composed masked reference within 1e-5 (f32 and bf16
  inputs), including nonzero per-row starts (chunked continuation and
  shared-prefix partial prefills) — ONE program shape for all of them;
- int8 fused writes bit-identical to the ``quantize_kv_block`` policy
  (merged old-prefix/fresh-chunk content, sanitize, fresh scales), the
  in-kernel qerr equal to the reference max-abs dequant error, and
  over-cover table entries routed to the scratch block untouched;
- the nested-shard_map variant at mesh 2 matches unsharded bitwise;
- engine end-to-end: greedy tokens bit-identical between
  ``prefill_impl="kernel"`` and ``"xla"`` (bf16 cache and int8 pool,
  chunked + prefix-hit traffic), frozen ``1 + len(prefill_buckets)``
  program contract re-pinned per (mesh, dtype);
- the int8 kernel program lowers STRICTLY fewer scatters than the
  ``_quant_prefill_write`` chain (the fused write removes the
  per-layer gather/requant/scatter round-trip);
- chaos re-run (prefill faults + NaN bursts) on the kernel path with
  zero slot/block/scale leaks, and the new telemetry (kernel span,
  fused-write counter, kernel-active gauge) captured schema-clean.
"""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.ops import quant
from nezha_tpu.ops.pallas import (
    flash_prefill_attention,
    flash_prefill_attention_sharded,
)
from nezha_tpu.serve import Engine, Request, Scheduler, ServeConfig

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
# kv_block_size 4 so the 12-token prompt spans real blocks: full-block
# prefix hits and mid-block continuation starts both fire at test sizes.
PCFG = ServeConfig(max_batch_size=3, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32, kv_block_size=4)
LONG = [5, 17, 3, 9, 11, 2, 7, 23, 41, 8, 1, 13]     # > max_prefill_len
PROMPTS = (LONG, [1, 2, 3], LONG)                    # 3rd = prefix hit

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _sub in ("tools",):
    _p = os.path.join(_ROOT, _sub)
    if _p not in sys.path:
        sys.path.insert(0, _p)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def engines(model_and_vars):
    """The four engines of the parity matrix — built once, reused by
    the parity, program-contract, and scatter-count pins (the frozen
    program set is the property that makes sharing safe)."""
    model, variables = model_and_vars
    out = {}
    for name, kw in (("bf16", dict(cache_dtype=jnp.bfloat16)),
                     ("int8", dict(kv_dtype="int8"))):
        for impl in ("kernel", "xla"):
            cfg = dataclasses.replace(PCFG, prefill_impl=impl, **kw)
            out[name, impl] = Engine(model, variables, cfg)
    return out


def _greedy(engine, prompts=PROMPTS, max_new=6):
    """Serial submit+drain so the repeated prompt takes a prefix hit."""
    sched = Scheduler(engine)
    outs = []
    for i, p in enumerate(prompts):
        rid = sched.submit(Request(prompt=list(p), max_new_tokens=max_new,
                                   request_id=f"r{i}"))
        sched.run_until_idle(max_iters=300)
        outs.append(list(sched.results[rid].tokens))
    return outs


# --------------------------------------------------- kernel-level refs
def _ref_attn(q, k_all, v_all, starts, s_chunk):
    """Dense masked reference: rows attend their pool prefix plus the
    causal part of their own chunk."""
    b = q.shape[0]
    outs = []
    for i in range(b):
        st = int(starts[i])
        ln = st + s_chunk
        k, v = k_all[i][:, :ln], v_all[i][:, :ln]
        s = np.einsum("hsd,hld->hsl", q[i], k) / np.sqrt(q.shape[-1])
        qpos = st + np.arange(s_chunk)
        mask = np.arange(ln)[None, :] <= qpos[:, None]
        s = np.where(mask[None], s, -1e30)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        outs.append(np.einsum("hsl,hld->hsd", p, v))
    return np.stack(outs)


def _case(rng, starts, *, b=2, h=4, d=16, bs=8, m=12, s_chunk=16,
          extra_blocks=0):
    """One kernel test case: per-row tables covering start+chunk (plus
    ``extra_blocks`` over-cover entries past the write window), float
    pools, and a fresh chunk."""
    n = 2 + sum((int(st) + s_chunk + bs - 1) // bs + extra_blocks
                for st in starts)
    pool_k = rng.randn(n, h, bs, d).astype(np.float32)
    pool_v = rng.randn(n, h, bs, d).astype(np.float32)
    tab = np.zeros((b, m), np.int32)
    used = 1
    for i in range(b):
        need = (int(starts[i]) + s_chunk + bs - 1) // bs + extra_blocks
        assert need <= m
        for j in range(need):
            tab[i, j] = used
            used += 1
    q = rng.randn(b, h, s_chunk, d).astype(np.float32)
    kc = rng.randn(b, h, s_chunk, d).astype(np.float32)
    vc = rng.randn(b, h, s_chunk, d).astype(np.float32)
    return q, kc, vc, pool_k, pool_v, tab


def _gather(pool_k, pool_v, tab, starts, kc, vc, bs, m, s_chunk,
            scales=None):
    """Dense [B,H,L,D] views: pool prefix (dequantized when ``scales``)
    then the fresh chunk at each row's start."""
    b, h, _, d = kc.shape
    k_all = np.zeros((b, h, m * bs, d), np.float32)
    v_all = np.zeros_like(k_all)
    for i in range(b):
        st = int(starts[i])
        for p_ in range(st):
            blk, off = tab[i, p_ // bs], p_ % bs
            kr = pool_k[blk, :, off].astype(np.float32)
            vr = pool_v[blk, :, off].astype(np.float32)
            if scales is not None:
                kr = kr * scales[0][blk][:, None]
                vr = vr * scales[1][blk][:, None]
            k_all[i, :, p_] = kr
            v_all[i, :, p_] = vr
        for j in range(s_chunk):
            k_all[i, :, st + j] = kc[i, :, j]
            v_all[i, :, st + j] = vc[i, :, j]
    return k_all, v_all


@pytest.mark.parametrize("starts", [(0, 0), (8, 24), (5, 13)],
                         ids=["cold", "block-aligned", "mid-block"])
def test_kernel_matches_masked_reference_f32(starts):
    """One compiled shape serves cold prefills, chunked continuations
    (block-aligned starts), and shared-prefix partial prefills
    (mid-block starts) — all within 1e-5 of the dense masked path."""
    rng = np.random.RandomState(0)
    bs, m, s_chunk = 8, 12, 16
    q, kc, vc, pk, pv, tab = _case(rng, starts, bs=bs, m=m,
                                   s_chunk=s_chunk)
    out = flash_prefill_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(pk), jnp.asarray(pv), jnp.asarray(tab),
        jnp.asarray(starts, jnp.int32), interpret=True)
    k_all, v_all = _gather(pk, pv, tab, starts, kc, vc, bs, m, s_chunk)
    ref = _ref_attn(q, k_all, v_all, starts, s_chunk)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_kernel_matches_masked_reference_bf16():
    """bf16 chunk + bf16 pool (the engine's bf16 cache layout): the
    kernel attends the same bf16-cast values the composed
    gather-after-write path sees, f32 accumulation, within 1e-5 of a
    reference computed from those cast values."""
    rng = np.random.RandomState(1)
    starts, bs, m, s_chunk = (8, 24), 8, 12, 16
    q, kc, vc, pk, pv, tab = _case(rng, starts, bs=bs, m=m,
                                   s_chunk=s_chunk)
    to_bf = lambda x: jnp.asarray(x, jnp.bfloat16)
    back = lambda x: np.asarray(jnp.asarray(to_bf(x), jnp.float32))
    out = flash_prefill_attention(
        to_bf(q), to_bf(kc), to_bf(vc), to_bf(pk), to_bf(pv),
        jnp.asarray(tab), jnp.asarray(starts, jnp.int32), interpret=True)
    k_all, v_all = _gather(back(pk), back(pv), tab, starts, back(kc),
                           back(vc), bs, m, s_chunk)
    ref = _ref_attn(back(q), k_all, v_all, starts, s_chunk)
    # The shared softmax core rounds probabilities to v.dtype (bf16)
    # exactly like the decode/flash kernels — the f32 reference can
    # only match to bf16 resolution; the ≤1e-5 acceptance is pinned by
    # the f32 kernel-vs-masked test above and by the engine's bf16
    # BIT-parity (kernel and composed path see the same cast values).
    np.testing.assert_allclose(
        np.asarray(jnp.asarray(out, jnp.float32)), ref, atol=2e-2)


@pytest.mark.parametrize("starts", [(0, 0), (5, 13)],
                         ids=["cold", "mid-block"])
def test_int8_fused_write_matches_quant_policy(starts):
    """The epilogue write IS ``_quant_prefill_write``: merged
    old-prefix/fresh-chunk rows, stale positions zeroed, sanitize,
    fresh per-(block, head) scales via the exact ``quantize_kv_block``
    policy — int8 pools bit-identical to the reference, scales to
    float tolerance, qerr equal to the reference max-abs dequant
    error and bounded by ``kv_roundtrip_error`` per merged block.
    Over-cover table entries (blocks past the write window) and the
    untouched rest of the pool come back byte-identical; scratch
    block 0 is zeroed with unit scales."""
    rng = np.random.RandomState(2)
    bs, m, s_chunk = 8, 12, 16
    q, kc, vc, pk_f, pv_f, tab = _case(rng, starts, bs=bs, m=m,
                                       s_chunk=s_chunk, extra_blocks=1)
    pk = rng.randint(-127, 128, pk_f.shape).astype(np.int8)
    pv = rng.randint(-127, 128, pv_f.shape).astype(np.int8)
    ks = (np.abs(rng.randn(*pk.shape[:2])) * 0.02 + 0.01).astype(
        np.float32)
    vs = (np.abs(rng.randn(*pv.shape[:2])) * 0.02 + 0.01).astype(
        np.float32)
    out, kp_n, vp_n, ks_n, vs_n, qerr = flash_prefill_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(pk), jnp.asarray(pv), jnp.asarray(tab),
        jnp.asarray(starts, jnp.int32),
        block_scales=(jnp.asarray(ks), jnp.asarray(vs)), interpret=True)
    kp_n, vp_n, ks_n, vs_n = map(np.asarray, (kp_n, vp_n, ks_n, vs_n))

    exp_kp, exp_vp = pk.copy(), pv.copy()
    exp_ks, exp_vs = ks.copy(), vs.copy()
    exp_kp[0] = 0
    exp_vp[0] = 0
    exp_ks[0] = 1.0
    exp_vs[0] = 1.0
    maxerr, rt_bound = 0.0, 0.0
    for i in range(len(starts)):
        st = int(starts[i])
        for t in range(st // bs, (st + s_chunk - 1) // bs + 1):
            blk = tab[i, t]
            wpos = t * bs + np.arange(bs)
            for pool, sc, ch, exp_p, exp_s in (
                    (pk, ks, kc, exp_kp, exp_ks),
                    (pv, vs, vc, exp_vp, exp_vs)):
                old = pool[blk].astype(np.float32) * sc[blk][:, None,
                                                            None]
                merged = np.zeros_like(old)
                for r in range(bs):
                    if wpos[r] < st:
                        merged[:, r] = old[:, r]
                    elif wpos[r] < st + s_chunk:
                        merged[:, r] = ch[i, :, wpos[r] - st]
                qn, sn = quant.quantize_kv_block(jnp.asarray(merged))
                exp_p[blk] = np.asarray(qn)
                exp_s[blk] = np.asarray(sn)
                deq = (np.asarray(qn).astype(np.float32)
                       * np.asarray(sn)[:, None, None])
                live = wpos < st + s_chunk
                maxerr = max(maxerr,
                             float(np.max(np.abs(merged - deq)[:, live])))
                rt_bound = max(rt_bound, float(
                    quant.kv_roundtrip_error(jnp.asarray(merged))))
    assert np.array_equal(kp_n, exp_kp)
    assert np.array_equal(vp_n, exp_vp)
    np.testing.assert_allclose(ks_n, exp_ks, rtol=1e-6)
    np.testing.assert_allclose(vs_n, exp_vs, rtol=1e-6)
    assert abs(float(qerr) - maxerr) < 1e-6
    assert float(qerr) <= rt_bound + 1e-6
    # Attention over the dequantized prefix + fresh chunk.
    k_all, v_all = _gather(pk, pv, tab, starts, kc, vc, bs, m, s_chunk,
                           scales=(ks, vs))
    ref = _ref_attn(q, k_all, v_all, starts, s_chunk)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


def test_sharded_mesh2_matches_unsharded():
    """The nested-shard_map variant (the sharded engine's path) is a
    pure reshard: attention equal to tolerance, int8 pools + scales
    BITWISE equal, qerr identical (pmax over head shards)."""
    from nezha_tpu.parallel.mesh import make_mesh

    rng = np.random.RandomState(3)
    starts, bs, m, s_chunk = (5, 13), 8, 12, 16
    q, kc, vc, pk, pv, tab = _case(rng, starts, bs=bs, m=m,
                                   s_chunk=s_chunk)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    args = (jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc))
    ref = flash_prefill_attention(
        *args, jnp.asarray(pk), jnp.asarray(pv), jnp.asarray(tab),
        jnp.asarray(starts, jnp.int32), interpret=True)
    got = flash_prefill_attention_sharded(
        *args, jnp.asarray(pk), jnp.asarray(pv), jnp.asarray(tab),
        jnp.asarray(starts, jnp.int32), mesh, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)

    pk8 = rng.randint(-127, 128, pk.shape).astype(np.int8)
    pv8 = rng.randint(-127, 128, pv.shape).astype(np.int8)
    ks = (np.abs(rng.randn(*pk.shape[:2])) * 0.02 + 0.01).astype(
        np.float32)
    vs = (np.abs(rng.randn(*pv.shape[:2])) * 0.02 + 0.01).astype(
        np.float32)
    q8 = (jnp.asarray(pk8), jnp.asarray(pv8), jnp.asarray(tab),
          jnp.asarray(starts, jnp.int32))
    scales = (jnp.asarray(ks), jnp.asarray(vs))
    ref8 = flash_prefill_attention(*args, *q8, block_scales=scales,
                                   interpret=True)
    got8 = flash_prefill_attention_sharded(*args, *q8, mesh,
                                           block_scales=scales,
                                           interpret=True)
    np.testing.assert_allclose(np.asarray(got8[0]), np.asarray(ref8[0]),
                               rtol=1e-6, atol=1e-6)
    for a, b in zip(got8[1:5], ref8[1:5]):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert float(got8[5]) == float(ref8[5])


# ----------------------------------------------- q_offsets (PR 20)
@pytest.mark.parametrize("starts", [(0, 0), (8, 24), (5, 13)],
                         ids=["cold", "block-aligned", "mid-block"])
def test_q_offsets_full_chunk_bitwise_default(starts):
    """``q_offsets=starts`` with a full-width query slab is the same
    computation as the legacy two-prefetch program — output BITWISE
    equal (the sequence-sharded engine's parity guarantee bottoms out
    here: a shard seeing the whole chunk reproduces the replicated
    path exactly)."""
    rng = np.random.RandomState(5)
    bs, m, s_chunk = 8, 12, 16
    q, kc, vc, pk, pv, tab = _case(rng, starts, bs=bs, m=m,
                                   s_chunk=s_chunk)
    A, st32 = jnp.asarray, jnp.asarray(starts, jnp.int32)
    ref = flash_prefill_attention(A(q), A(kc), A(vc), A(pk), A(pv),
                                  A(tab), st32, interpret=True)
    got = flash_prefill_attention(A(q), A(kc), A(vc), A(pk), A(pv),
                                  A(tab), st32, q_offsets=st32,
                                  interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(ref))


@pytest.mark.parametrize("starts", [(8, 24), (5, 13)],
                         ids=["block-aligned", "mid-block"])
def test_q_offsets_shard_slices_bitwise(starts):
    """The sequence-shard read layout: each of two query half-slabs
    (``S_q = S_kc / 2``) at ``q_offsets = starts + k * S_q`` against
    the FULL chunk K/V equals the corresponding slice of the
    full-width output bitwise — chunked continuations and mid-block
    shared-prefix starts both stay traced scalars in ONE program per
    (S_q, S_kc) signature."""
    rng = np.random.RandomState(6)
    bs, m, s_chunk = 8, 12, 16
    q, kc, vc, pk, pv, tab = _case(rng, starts, bs=bs, m=m,
                                   s_chunk=s_chunk)
    A, st32 = jnp.asarray, jnp.asarray(starts, jnp.int32)
    full = np.asarray(flash_prefill_attention(
        A(q), A(kc), A(vc), A(pk), A(pv), A(tab), st32, interpret=True))
    half = s_chunk // 2
    for k in range(2):
        got = flash_prefill_attention(
            A(q[:, :, k * half:(k + 1) * half]), A(kc), A(vc), A(pk),
            A(pv), A(tab), st32, q_offsets=st32 + k * half,
            interpret=True)
        assert np.array_equal(np.asarray(got),
                              full[:, :, k * half:(k + 1) * half])


def test_q_offsets_rejects_int8_pools():
    """``q_offsets`` is a read-layout feature of the float path; the
    int8 fused write needs the full chunk's queries resident, so the
    combination is a typed refusal, not silent corruption."""
    rng = np.random.RandomState(7)
    starts, bs, m, s_chunk = (8, 24), 8, 12, 16
    q, kc, vc, pk, pv, tab = _case(rng, starts, bs=bs, m=m,
                                   s_chunk=s_chunk)
    pk8 = rng.randint(-127, 128, pk.shape).astype(np.int8)
    pv8 = rng.randint(-127, 128, pv.shape).astype(np.int8)
    ks = np.ones(pk.shape[:2], np.float32)
    vs = np.ones(pv.shape[:2], np.float32)
    A, st32 = jnp.asarray, jnp.asarray(starts, jnp.int32)
    with pytest.raises(ValueError, match="float path"):
        flash_prefill_attention(
            A(q), A(kc), A(vc), A(pk8), A(pv8), A(tab), st32,
            block_scales=(A(ks), A(vs)), q_offsets=st32, interpret=True)


# ------------------------------------------------------- engine parity
@pytest.mark.parametrize("dtype", ["bf16", "int8"])
def test_engine_greedy_parity_and_frozen_programs(engines, dtype):
    """End-to-end through the engine: greedy tokens BIT-IDENTICAL
    between the kernel and composed-XLA prefill under chunked +
    prefix-hit traffic, with the frozen ``1 + len(prefill_buckets)``
    program contract re-pinned on BOTH impls (the kernel replaces the
    chunk attention + write inside the same per-bucket program — it
    must not add one)."""
    ek, ex = engines[dtype, "kernel"], engines[dtype, "xla"]
    assert ek.prefill_kernel_active and not ex.prefill_kernel_active
    tk, tx = _greedy(ek), _greedy(ex)
    assert tk == tx
    for eng in (ek, ex):
        stats = eng.compile_stats()
        assert stats["entries"] == 1 + len(PCFG.prefill_buckets)
        assert eng.pool.prefix_hits >= 1          # 3rd prompt re-hit
        eng.pool.leak_check()


def test_mesh2_engine_kernel_parity(model_and_vars):
    """``prefill_impl="kernel"`` under the mesh routes through the
    nested-shard_map variant and stays bit-identical to the
    single-device forced-kernel int8 engine, same frozen program
    count (the per-mesh re-pin)."""
    from nezha_tpu.serve.sharded import ShardedEngine

    model, variables = model_and_vars
    cfg = dataclasses.replace(PCFG, prefill_impl="kernel",
                              kv_dtype="int8")
    ref = _greedy(Engine(model, variables, cfg))
    eng = ShardedEngine(model, variables, cfg, mesh_devices=2)
    assert eng.prefill_kernel_active
    assert _greedy(eng) == ref
    stats = eng.compile_stats()
    assert stats["entries"] == 1 + len(PCFG.prefill_buckets)
    eng.pool.leak_check()


def test_int8_kernel_strictly_fewer_scatters(engines):
    """The fused epilogue write removes the per-layer gather/requant/
    scatter round-trip: the kernel bucket program lowers STRICTLY
    fewer scatter ops than the ``_quant_prefill_write`` chain (the
    'fewer compiled programs' acceptance, measured at the HLO level
    where the round-trip actually lives)."""
    counts = {}
    for impl in ("kernel", "xla"):
        eng = engines["int8", impl]
        width = max(PCFG.prefill_buckets)
        scalars = (np.int32(width), np.int32(0), np.int32(0),
                   np.int32(0), np.float32(0.0), np.int32(0),
                   np.float32(1.0), np.int32(-1), np.int32(6))
        state = (eng.last_logits, eng.positions, eng.keys, eng.temps,
                 eng.top_ks, eng.top_ps, eng.eos_ids, eng.budgets)
        lowered = jax.jit(eng._prefill_fns[width]).lower(
            eng.variables, eng.pool.caches,
            jnp.asarray(eng.pool.tables_host),
            jnp.zeros((1, width), jnp.int32), *scalars, *state)
        counts[impl] = lowered.as_text().count("scatter")
    assert counts["kernel"] < counts["xla"], counts


# --------------------------------------------------- chaos + telemetry
def test_chaos_kernel_prefill_zero_leaks_and_telemetry(model_and_vars,
                                                       tmp_path):
    """The chaos acceptance re-run on the kernel path: seeded prefill
    errors + NaN bursts over templated int8 traffic (prefix hits and
    chunked continuations in play). Every request resolves, zero
    slot/block/scale leaks, frozen programs — and the run captures
    the PR's telemetry schema-clean: ``serve.prefill.kernel_s`` spans,
    a nonzero ``serve.prefill.fused_writes_total``, the kernel-active
    gauge, and the report's ``prefill[kernel]`` label."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "chaos_prefill_kernel")
    obs.start_run(run_dir, meta={"kind": "chaos_prefill_kernel"})
    try:
        cfg = dataclasses.replace(PCFG, prefill_impl="kernel",
                                  kv_dtype="int8", queue_capacity=12)
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        faults.install(faults.FaultPlan.parse(
            "serve.prefill:error%0.1;serve.prefill.logits:nan%0.1",
            seed=11))
        try:
            rids = []
            for i in range(12):
                prompt = (LONG[:8] + [i % 97]
                          if i % 2 else
                          [(7 * i + j) % 97 for j in range(6)])
                rids.append(sched.submit(Request(
                    prompt=prompt, max_new_tokens=4,
                    request_id=f"c{i}")))
            sched.run_until_idle(max_iters=600)
            assert not sched.has_work()
        finally:
            faults.clear()
        assert set(rids) <= set(sched.results)
        reasons = {sched.results[r].finish_reason for r in rids}
        assert reasons <= {"length", "error"}
        assert eng.pool.num_free == cfg.max_batch_size
        eng.pool.leak_check()
        stats = eng.compile_stats()
        assert stats["entries"] == 1 + len(cfg.prefill_buckets)
        eng.pool.clear_prefix_cache()
        eng.pool.leak_check()
        assert eng.pool.blocks_used == 0
        assert obs.counter("serve.prefill.fused_writes_total").value > 0
    finally:
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert summary["gauges"]["serve.prefill.kernel_active"] == 1
    assert summary["counters"]["serve.prefill.fused_writes_total"] > 0
    with open(os.path.join(run_dir, "spans.jsonl")) as f:
        span_names = {json.loads(ln)["name"] for ln in f if ln.strip()}
    assert "serve.prefill.kernel_s" in span_names
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "prefill[kernel, replicated]:" in report
    assert "fused writes" in report
    # Dropping the new instruments must FAIL the pinned schema.
    del summary["counters"]["serve.prefill.fused_writes_total"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.prefill.fused_writes_total" in e
               for e in check_run_dir(run_dir))


def test_env_escape_hatch_kills_kernel(model_and_vars, monkeypatch):
    """``NEZHA_NO_PREFILL_KERNEL=1`` beats even an explicit
    ``prefill_impl="kernel"`` — the day-1 rollback needs no config
    push — and the gauge reports the fallback."""
    model, variables = model_and_vars
    monkeypatch.setenv("NEZHA_NO_PREFILL_KERNEL", "1")
    cfg = dataclasses.replace(PCFG, prefill_impl="kernel")
    eng = Engine(model, variables, cfg)
    assert not eng.prefill_kernel_active


def test_serve_config_validates_prefill_impl():
    with pytest.raises(ValueError, match="prefill_impl"):
        ServeConfig(prefill_impl="mosaic")
