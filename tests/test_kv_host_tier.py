"""Tiered KV cache (ISSUE 15): host-memory spill of evicted int8
blocks with async promote-on-hit.

Covers the tier lifecycle end to end: demote→promote BIT-IDENTITY
(the int8 payload + per-(block, head) scales round-trip exactly — a
promoted block is byte-for-byte the block that was evicted, proven
against a never-evicted gather), token-level parity of a promoted
revisit against a cold engine, the host-LRU budget cap, a promotion
whose own allocations trigger concurrent eviction/demotion
(promote-racing-eviction), the ``serve.kv.promote`` fault point
(failed promote degrades to a cold prefill — typed, counted, nothing
surfaced to the request), a seeded chaos run with ZERO device and
host block leaks + schema-valid artifacts carrying the new pinned
instruments, the ``kv_eviction="none"``/bf16 refusal surface (those
pools are unchanged — the tier is int8 + lru + prefix-cache only),
the CLI/bench plumbing (``--kv-host-blocks`` parse, worker argv
passthrough, the churn record), and the nezha-bench ``kv_churn`` gate
rows.
"""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import (
    Engine,
    PagedSlotPool,
    Request,
    Scheduler,
    ServeConfig,
)
from nezha_tpu.serve.slots import _gather_blocks_quantized_jit

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
# Host-tier serving shapes: block_size 4 + a small block budget so
# eviction (hence demotion) fires at test sizes, int8 blocks (the
# tier's storage precondition), a generous host budget.
HCFG = ServeConfig(max_batch_size=2, max_len=32, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=8,
                   cache_dtype=jnp.float32, kv_block_size=4,
                   kv_num_blocks=9, kv_dtype="int8", kv_host_blocks=16)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for sub in ("tools", "benchmarks"):
    p = os.path.join(_ROOT, sub)
    if p not in sys.path:
        sys.path.insert(0, p)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


def _drain(sched, max_iters=400):
    sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"


def _gather_host(pool, blocks):
    """Block payloads as host arrays (the demote capture, done by
    hand): per-layer {k, v, k_scale, v_scale} for ``blocks``."""
    idx = jnp.asarray(np.asarray(blocks, np.int32))
    return [{k: np.asarray(v) for k, v in layer.items()}
            for layer in _gather_blocks_quantized_jit(pool.caches, idx)]


def _assert_payload_equal(a, b):
    assert len(a) == len(b)
    for la, lb in zip(a, b):
        assert set(la) == set(lb) == {"k", "v", "k_scale", "v_scale"}
        for key in la:
            np.testing.assert_array_equal(la[key], lb[key])


# -------------------------------------------------- config validation
def test_host_tier_config_validation():
    with pytest.raises(ValueError, match="kv_host_blocks"):
        ServeConfig(kv_host_blocks=-1)
    # int8-only: the demoted payload is the wire-format bytes verbatim.
    with pytest.raises(ValueError, match="int8"):
        ServeConfig(kv_host_blocks=8)
    with pytest.raises(ValueError, match="paged"):
        ServeConfig(kv_layout="dense", kv_host_blocks=8)
    with pytest.raises(ValueError, match="prefix_cache"):
        ServeConfig(kv_dtype="int8", prefix_cache=False,
                    kv_host_blocks=8)
    with pytest.raises(ValueError, match="lru"):
        ServeConfig(kv_dtype="int8", kv_eviction="none",
                    kv_host_blocks=8)


def test_host_tier_pool_validation(model_and_vars):
    model, _ = model_and_vars
    with pytest.raises(ValueError, match="quantized"):
        PagedSlotPool(model, capacity=1, max_len=16,
                      block_size=4, host_blocks=4)
    with pytest.raises(ValueError, match="prefix_cache"):
        PagedSlotPool(model, capacity=1, max_len=16, block_size=4,
                      quantized=True, prefix_cache=False, host_blocks=4)


# ------------------------------------------------- demote -> promote
def test_demote_promote_bit_identity_and_token_parity(model_and_vars):
    """THE tier contract: a demoted block's int8 payload + scales come
    back bit-identical on promotion (compared against a gather taken
    BEFORE eviction), and the promoted revisit decodes token-for-token
    what a cold engine produces. The promote is observable: the
    revisit's device trie match is empty (its blocks were evicted),
    promotions fire, and the prefill shrinks to one tail chunk."""
    model, variables = model_and_vars
    eng = Engine(model, variables, HCFG)
    sched = Scheduler(eng)
    prompt_a = [(3 * i + 5) % 97 for i in range(10)]    # 2 full blocks
    a = sched.submit(Request(prompt=prompt_a, max_new_tokens=2,
                             request_id="a"))
    _drain(sched)
    cached = eng.pool.trie.match(prompt_a)
    assert len(cached) == 2
    before = _gather_host(eng.pool, cached)

    # Pressure: a 30-token prompt binds every usable block (span 32 =
    # 8 blocks), evicting — hence DEMOTING — both of A's cached blocks.
    b = sched.submit(Request(prompt=[(7 * i + 1) % 97 for i in range(30)],
                             max_new_tokens=2, request_id="b"))
    _drain(sched)
    assert eng.pool.trie.match(prompt_a) == []
    assert eng.pool.demotions >= 2
    assert eng.pool.host_blocks_used >= 2
    # The demoted entries ARE the pre-eviction bytes (keyed by the
    # full prefix path).
    entry1 = eng.pool._host_tier[tuple(prompt_a[:4])]
    entry2 = eng.pool._host_tier[tuple(prompt_a[:8])]
    _assert_payload_equal(
        [{k: v[:1] for k, v in layer.items()} for layer in before],
        entry1)
    _assert_payload_equal(
        [{k: v[1:2] for k, v in layer.items()} for layer in before],
        entry2)

    # Revisit: promote-on-hit. The tail differs (turn N+1), so only
    # the 8-position full-block prefix is served from the tier.
    obs_run = obs.counter("serve.kv.promotions_total").value
    prompt_a2 = prompt_a[:8] + [33, 44]
    a2 = sched.submit(Request(prompt=prompt_a2, max_new_tokens=2,
                              request_id="a2"))
    _drain(sched)
    assert eng.pool.promotions >= 2
    promoted = eng.pool.trie.match(prompt_a2)
    assert len(promoted) == 2
    _assert_payload_equal(before, _gather_host(eng.pool, promoted))
    # Exclusive move: the promoted entries left the host tier.
    assert tuple(prompt_a[:4]) not in eng.pool._host_tier
    assert tuple(prompt_a[:8]) not in eng.pool._host_tier
    eng.pool.leak_check()

    # Token parity vs a never-tiered cold engine.
    cold = Engine(model, variables, dataclasses.replace(
        HCFG, kv_host_blocks=0, prefix_cache=False))
    sc = Scheduler(cold)
    ref = sc.submit(Request(prompt=prompt_a2, max_new_tokens=2))
    _drain(sc)
    assert sched.results["a2"].tokens == sc.results[ref].tokens
    assert sched.results["a2"].finish_reason == "length"
    del a, b, a2, obs_run


def test_host_lru_budget_cap(model_and_vars):
    """The host budget is a hard cap: demotions past it drop the
    OLDEST entries (for good — there is no colder tier), occupancy and
    byte accounting stay consistent, and leak_check's host column
    passes throughout."""
    model, variables = model_and_vars
    eng = Engine(model, variables,
                 dataclasses.replace(HCFG, kv_host_blocks=2))
    sched = Scheduler(eng)
    prompts = [[(11 * u + 3 * i + 5) % 97 for i in range(10)]
               for u in range(3)]
    for u, p in enumerate(prompts):
        sched.submit(Request(prompt=p, max_new_tokens=2,
                             request_id=f"u{u}"))
        _drain(sched)
    # Keep evicting: a wide prompt flushes whatever is still cached.
    sched.submit(Request(prompt=[(7 * i + 2) % 97 for i in range(30)],
                         max_new_tokens=2))
    _drain(sched)
    pool = eng.pool
    assert pool.demotions > 2                  # more demoted than fits
    assert pool.host_blocks_used <= 2          # the cap held
    assert pool.host_bytes_resident == sum(
        pool._entry_bytes(e) for e in pool._host_tier.values())
    pool.leak_check()
    # Entries are dropped oldest-first: whatever remains was demoted
    # LAST (the wide prompt's own cached blocks, once evicted later,
    # or the youngest user's) — the first user's first block is gone.
    assert tuple(prompts[0][:4]) not in pool._host_tier


def test_promote_racing_concurrent_eviction(model_and_vars):
    """A promotion whose own allocations trigger eviction — hence
    demotion of OTHER entries mid-promote — must succeed with balanced
    books: the popped entries can't be raced away by the host LRU, the
    evicted third party lands in the tier, and the promoted content is
    still bit-identical."""
    model, _ = model_and_vars
    pool = PagedSlotPool(model, capacity=3, max_len=16,
                         dtype=jnp.float32, block_size=4, num_blocks=6,
                         quantized=True, host_blocks=8)
    t1 = [(3 * i + 1) % 97 for i in range(9)]      # 2 full blocks + 1
    t2 = [(5 * i + 2) % 97 for i in range(9)]      # 2 full blocks + 1
    t3 = [(7 * i + 3) % 97 for i in range(12)]     # 3 blocks
    s = pool.alloc()
    pool.bind_for_prompt(s, t1)
    pool.prepare_write(s, 0, 9)
    pool.register_prefix(s, t1)
    t1_bytes = _gather_host(pool, [int(b) for b in
                                   pool.tables_host[s, :2]])
    pool.free(s)                                   # t1 cached: 2 blocks
    s = pool.alloc()
    pool.bind_for_prompt(s, t2)
    pool.prepare_write(s, 0, 9)
    pool.register_prefix(s, t2)
    pool.free(s)                                   # t2 cached: 2 blocks
    # t3 binds 3: free list holds 1, so 2 LRU evictions DEMOTE t1's
    # chain; t3 stays LIVE so its blocks pin the pool.
    s3 = pool.alloc()
    pool.bind_for_prompt(s3, t3)
    pool.prepare_write(s3, 0, 12)
    assert pool.demotions == 2
    assert [b for b in pool.trie.match(t1)] == []
    # Revisit t1: promotion needs 2 blocks; free list is EMPTY and the
    # only reclaimable blocks are t2's cached pair — the promote's own
    # _alloc_block calls evict+demote them, racing the host tier the
    # promote is concurrently reading.
    s4 = pool.alloc()
    shared = pool.bind_for_prompt(s4, t1)
    assert shared == 8                       # 2 promoted full blocks
    assert pool.promotions == 2
    assert pool.demotions == 4               # t2's pair demoted DURING
    assert pool.trie.match(t2) == []
    assert tuple(t2[:4]) in pool._host_tier
    _assert_payload_equal(
        t1_bytes,
        _gather_host(pool, [int(b) for b in pool.tables_host[s4, :2]]))
    pool.leak_check()
    pool.free(s4)
    pool.free(s3)
    pool.leak_check()
    pool.clear_prefix_cache()
    assert pool.blocks_used == 0
    assert pool.clear_host_tier() > 0
    pool.leak_check()


def test_promote_never_exceeds_admission_budget_on_aligned_prompt(
        model_and_vars):
    """The admission-budget invariant: a promote-path prefill of a
    BLOCK-ALIGNED prompt (whose final block would COW immediately —
    the last token always re-runs) must allocate no more device blocks
    than the cold footprint the scheduler budgeted. The promote scan
    caps at (n-1)//bs, so the guaranteed-COW block re-prefills instead
    of being promoted-then-copied — and the request still succeeds on
    a pool at exactly the admission edge."""
    model, variables = model_and_vars
    eng = Engine(model, variables, HCFG)
    sched = Scheduler(eng)
    prompt = [(3 * i + 5) % 97 for i in range(8)]   # exactly 2 blocks
    sched.submit(Request(prompt=prompt, max_new_tokens=2))
    _drain(sched)
    sched.submit(Request(prompt=[(7 * i + 1) % 97 for i in range(30)],
                         max_new_tokens=2))
    _drain(sched)                        # prompt's blocks now host-only
    assert eng.pool.host_blocks_used >= 2
    need = eng.prefill_blocks_needed(len(prompt))
    used_before = eng.pool.blocks_used
    slot = eng.pool.alloc()
    eng.prefill(slot, prompt, max_new_tokens=2)
    # Only the promotable span (block 0) came back; block 1 — which
    # would have COWed — re-prefilled cold, keeping the allocation
    # within the admission budget.
    assert eng.pool.promotions == 1
    assert eng.pool.blocks_used - used_before <= need
    eng.pool.free(slot)
    eng.pool.leak_check()


def test_failed_promote_restore_reapplies_host_budget_cap(
        model_and_vars):
    """A promote that fails MID-allocation (after some allocs already
    evicted-and-demoted third-party blocks into a tier at budget) must
    restore its popped entries WITHOUT busting the hard cap: the LRU
    trim re-applies on the degrade path, leak_check's host column
    holds, and nothing on either tier leaks."""
    model, _ = model_and_vars
    pool = PagedSlotPool(model, capacity=3, max_len=16,
                         dtype=jnp.float32, block_size=4, num_blocks=6,
                         quantized=True, host_blocks=2)
    t1 = [(3 * i + 1) % 97 for i in range(9)]
    t2 = [(5 * i + 2) % 97 for i in range(9)]
    for toks in (t1, t2):
        s = pool.alloc()
        pool.bind_for_prompt(s, toks)
        pool.prepare_write(s, 0, 9)
        pool.register_prefix(s, toks)
        pool.free(s)
    # t3 live: binds 3, demoting t1's chain — tier now AT its cap of 2.
    s3 = pool.alloc()
    pool.bind_for_prompt(s3, [(7 * i + 3) % 97 for i in range(12)])
    pool.prepare_write(s3, 0, 12)
    assert pool.host_blocks_used == 2
    # Revisit t1: the promote pops both entries, its first alloc
    # demotes a t2 block into the tier, then the second alloc dies on
    # an injected bind fault — the restore path must trim back to cap.
    s4 = pool.alloc()
    try:
        faults.install(faults.FaultPlan.parse("serve.kv.bind:error@2"))
        assert pool.bind_for_prompt(s4, t1) == 0   # degraded: cold
    finally:
        faults.clear()
    assert pool.promote_failures == 1 and pool.promotions == 0
    assert pool.host_blocks_used <= 2
    pool.leak_check()
    pool.free(s4)
    pool.free(s3)
    pool.clear_prefix_cache()
    pool.clear_host_tier()
    pool.leak_check()
    assert pool.blocks_used == 0


# ------------------------------------------------------- fault point
def test_promote_fault_degrades_to_cold_prefill(model_and_vars):
    """The serve.kv.promote fault point: an injected promote failure
    DEGRADES the request to a cold prefill — served correctly, typed +
    counted (promote_failures, faults.injected_total), the demoted
    entries left resident for the next hit — and the next promote
    (fault exhausted) succeeds."""
    model, variables = model_and_vars
    eng = Engine(model, variables, HCFG)
    sched = Scheduler(eng)
    prompt = [(3 * i + 5) % 97 for i in range(10)]
    sched.submit(Request(prompt=prompt, max_new_tokens=2))
    _drain(sched)
    sched.submit(Request(prompt=[(7 * i + 1) % 97 for i in range(30)],
                         max_new_tokens=2))
    _drain(sched)                       # prompt's blocks now host-only
    assert eng.pool.host_blocks_used >= 2
    cold = Engine(model, variables, dataclasses.replace(
        HCFG, kv_host_blocks=0, prefix_cache=False))
    sc = Scheduler(cold)
    ref = sc.submit(Request(prompt=prompt, max_new_tokens=2))
    _drain(sc)
    try:
        faults.install(faults.FaultPlan.parse("serve.kv.promote:error@1"))
        r1 = sched.submit(Request(prompt=prompt, max_new_tokens=2,
                                  request_id="r1"))
        _drain(sched)
    finally:
        faults.clear()
    res = sched.results[r1]
    assert res.finish_reason == "length"        # served, not errored
    assert res.tokens == sc.results[ref].tokens
    assert eng.pool.promotions == 0
    assert eng.pool.promote_failures == 1
    # Degrade left the entries host-resident; the cold prefill then
    # re-registered the prefix on device, so the next identical
    # request is a DEVICE hit (no promote needed) — and the books
    # balance either way.
    assert tuple(prompt[:4]) in eng.pool._host_tier
    eng.pool.leak_check()


# ------------------------------------------------------------- chaos
def test_chaos_host_tier_zero_leaks(model_and_vars, tmp_path):
    """Seeded chaos over churning templated traffic with the host tier
    in play: prefill errors + NaN bursts + kv.bind failures + promote
    failures. Every request gets exactly one result, the device books
    balance AND the host column holds (zero leaks on both tiers), the
    program set stays frozen (promotion adds none), and the artifacts
    pass the pinned schema including the new serve.kv.* instruments;
    the report renders the host-tier segment."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "chaos_host_tier")
    obs.start_run(run_dir, meta={"kind": "chaos_host_tier"})
    try:
        cfg = dataclasses.replace(HCFG, queue_capacity=32)
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        faults.install(faults.FaultPlan.parse(
            "serve.prefill:error%0.08;serve.step.logits:nan%0.05;"
            "serve.kv.bind:error%0.02;serve.kv.promote:error%0.3",
            seed=11))
        try:
            users = [[(13 * u + 3 * i + 5) % 97 for i in range(10)]
                     for u in range(4)]
            rids = []
            for i in range(20):
                prompt = (users[i % 4][:8] + [i % 97, (2 * i) % 97]
                          if i >= 4 else users[i % 4])
                rids.append(sched.submit(Request(
                    prompt=prompt, max_new_tokens=4,
                    temperature=0.8 if i % 3 == 0 else 0.0,
                    top_k=10 if i % 3 == 0 else None, seed=i,
                    request_id=f"c{i}")))
            _drain(sched)
        finally:
            faults.clear()
        assert set(rids) <= set(sched.results)
        reasons = {sched.results[r].finish_reason for r in rids}
        assert reasons <= {"length", "error"}
        assert eng.pool.demotions > 0          # the tier actually churned
        # Zero slot leaks, zero DEVICE block leaks, zero HOST leaks
        # (budget + byte books + geometry), frozen programs.
        assert eng.pool.num_free == cfg.max_batch_size
        eng.pool.leak_check()
        stats = eng.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(cfg.prefill_buckets)
        eng.pool.clear_prefix_cache()
        eng.pool.clear_host_tier()
        eng.pool.leak_check()
        assert eng.pool.blocks_used == 0
        assert eng.pool.host_blocks_used == 0
    finally:
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    assert summary["counters"]["serve.kv.demotions_total"] > 0
    assert "serve.kv.promotions_total" in summary["counters"]
    assert "serve.kv.host_blocks_used" in summary["gauges"]
    assert "serve.kv.host_bytes_resident" in summary["gauges"]
    # Dropping a host-tier instrument must FAIL the pinned schema.
    del summary["counters"]["serve.kv.demotions_total"]
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    assert any("serve.kv.demotions_total" in e
               for e in check_run_dir(run_dir))
    summary["counters"]["serve.kv.demotions_total"] = 3
    with open(os.path.join(run_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "kv host tier:" in report and "demoted" in report


# --------------------------------------- unchanged-behavior surfaces
def test_no_host_tier_and_bf16_pools_unchanged(model_and_vars):
    """kv_host_blocks=0 (the default) and bf16 pools behave exactly as
    before: no demotions ever, eviction discards, the host gauges and
    ledgers read 0 — and kv_eviction='none' still surfaces typed
    backpressure with an inert tier surface."""
    model, variables = model_and_vars
    for cfg in (dataclasses.replace(HCFG, kv_host_blocks=0),
                dataclasses.replace(HCFG, kv_host_blocks=0,
                                    kv_dtype="bf16"),
                dataclasses.replace(HCFG, kv_host_blocks=0,
                                    kv_dtype="bf16",
                                    kv_eviction="none")):
        eng = Engine(model, variables, cfg)
        sched = Scheduler(eng)
        sched.submit(Request(prompt=[(3 * i + 5) % 97
                                     for i in range(10)],
                             max_new_tokens=2))
        _drain(sched)
        sched.submit(Request(prompt=[(7 * i + 1) % 97
                                     for i in range(30)],
                             max_new_tokens=2))
        _drain(sched)
        assert eng.pool.demotions == 0
        assert eng.pool.promotions == 0
        assert eng.pool.host_blocks_used == 0
        assert eng.pool.host_bytes_resident == 0
        eng.pool.leak_check()


# ------------------------------------------------- CLI + bench surface
def test_serve_cli_host_blocks_plumbing():
    """--kv-host-blocks parses, flows into the worker argv (the
    --replicas passthrough), and build_parser defaults it off."""
    from nezha_tpu.cli.serve import _worker_argv, build_parser

    args = build_parser().parse_args(
        ["--random-init", "--kv-dtype", "int8",
         "--kv-host-blocks", "48"])
    assert args.kv_host_blocks == 48
    argv = _worker_argv(args, rid=0, port=9999)
    assert argv[argv.index("--kv-host-blocks") + 1] == "48"
    assert build_parser().parse_args(
        ["--random-init"]).kv_host_blocks == 0


def test_serving_benchmark_kv_churn_record(model_and_vars):
    """benchmarks/serving.py --churn-users + --kv-host-blocks: the
    churn record carries the first-visit/revisit TTFT split and the
    demote/promote ledgers, promotions actually fire (the pool is
    sized so users' blocks cycle between visits), and the kv block
    reports the host-tier fields."""
    import serving as bench

    rec = bench.run(bench.build_parser().parse_args(
        ["--requests", "12", "--concurrency", "1",
         "--churn-users", "4", "--churn-prefix-len", "16",
         "--kv-block-size", "4", "--kv-dtype", "int8",
         "--kv-host-blocks", "32", "--max-batch-size", "2",
         "--max-len", "24", "--max-prefill-len", "8",
         "--kv-num-blocks", "13", "--max-new-tokens", "4",
         "--sample-fraction", "0"]))
    assert rec["finished"] == 12
    ch = rec["kv_churn"]
    assert ch["users"] == 4 and ch["prefix_len"] == 16
    assert ch["demotions"] > 0 and ch["promotions"] > 0
    assert ch["ttft_first_visit_s"]["p50"] > 0
    assert ch["ttft_revisit_s"]["p50"] > 0
    assert ch["revisit_vs_first_ttft_p50"] > 0
    kv = rec["kv"]
    assert kv["host_blocks"] == 32
    assert kv["demotions"] == ch["demotions"]
    assert kv["promotions"] == ch["promotions"]
    assert kv["peak_host_blocks_used"] > 0
    # Churn prefixes must be block-aligned — a misaligned length is a
    # typed refusal, not silent partial caching.
    with pytest.raises(SystemExit, match="multiple"):
        bench.run(bench.build_parser().parse_args(
            ["--churn-users", "2", "--churn-prefix-len", "10",
             "--kv-block-size", "4", "--kv-dtype", "int8"]))


def test_nezha_bench_kv_churn_gate_rows():
    """The kv_churn gate logic (no model run — cooked results): the
    promote-vs-cold ratio is a HARD gate at 0.5, promotions must be
    nonzero, and a committed baseline adds a drift gate."""
    from nezha_tpu.cli import bench as nb

    good = {"kv_churn": {"promote_vs_cold_ttft_p50": 0.38,
                         "promotions": 72}}
    rows = nb._gate(good, {}, "cpu", 0.30)["serving"]
    assert rows["kv_churn.promote_vs_cold_ttft_p50"]["ok"]
    assert rows["kv_churn.promotions"]["ok"]

    bad = {"kv_churn": {"promote_vs_cold_ttft_p50": 0.8,
                        "promotions": 0}}
    rows = nb._gate(bad, {}, "cpu", 0.30)["serving"]
    assert not rows["kv_churn.promote_vs_cold_ttft_p50"]["ok"]
    assert not rows["kv_churn.promotions"]["ok"]

    base = {"by_platform": {"cpu": {
        "kv_churn": {"promote_vs_cold_ttft_p50": 0.30}}}}
    rows = nb._gate(good, {"serving": base}, "cpu", 0.30)["serving"]
    drift = rows["kv_churn.promote_vs_cold_ttft_p50_vs_baseline"]
    assert drift["ok"]                      # 0.38/0.30 = 1.27 <= 1.30
    rows = nb._gate(good, {"serving": base}, "cpu", 0.10)["serving"]
    assert not rows[
        "kv_churn.promote_vs_cold_ttft_p50_vs_baseline"]["ok"]
