"""Flash-decode kernel: interpret-mode numerics at the edge rows the
serving engine actually produces (length 1, length == L_max, inactive
rows, mixed skews), greedy-decode parity between the kernel and the
composed masked path through the full model, and the microbenchmark's
tier-1 smoke. The kernel is the serving hot path — parity here is what
licenses `attn_impl="auto"` to route production decode through it."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import ops
from nezha_tpu.ops.pallas import flash_decode_attention


def _qkv(b, L, h=4, d=16, seed=0, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(kq, (b, h, 1, d), dtype),
            jax.random.normal(kk, (b, h, L, d), dtype),
            jax.random.normal(kv, (b, h, L, d), dtype))


def _composed(q, k, v, lengths):
    """The engine's pre-kernel decode path: dense attention under a
    [B, 1, 1, L] additive -inf mask."""
    L = k.shape[2]
    mask = jnp.where(jnp.arange(L)[None, :] < lengths[:, None],
                     0.0, -jnp.inf).astype(jnp.float32)
    return ops.dot_product_attention(q, k.astype(q.dtype),
                                     v.astype(q.dtype),
                                     mask=mask[:, None, None, :])


@pytest.mark.parametrize("lengths", [
    [1, 1, 1, 1],            # every row at minimum depth
    [48, 48, 48, 48],        # every row at full capacity
    [1, 48, 7, 23],          # mixed skew
    [5, 48, 1, 17],
])
def test_decode_kernel_matches_composed(lengths):
    q, k, v = _qkv(b=4, L=48)
    lengths = jnp.asarray(lengths, jnp.int32)
    out = flash_decode_attention(q, k, v, lengths, block_k=16)
    ref = _composed(q, k, v, lengths)
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() <= 1e-5


def test_decode_kernel_inactive_rows_zero():
    """length == 0 marks an inactive slot: every KV block is skipped and
    the output row is exactly zero (the composed path would compute a
    uniform softmax over garbage there)."""
    q, k, v = _qkv(b=3, L=32)
    out = flash_decode_attention(
        q, k, v, jnp.asarray([0, 32, 0], jnp.int32), block_k=16)
    out = np.asarray(out)
    assert (out[0] == 0).all() and (out[2] == 0).all()
    ref = _composed(q, k, v, jnp.asarray([32, 32, 32], jnp.int32))
    assert np.abs(out[1] - np.asarray(ref[1])).max() <= 1e-5


def test_decode_kernel_bf16_cache_fp32_accum():
    """bf16 q/K/V with fp32 accumulation: close to the fp32 composed
    reference at bf16-level tolerance, and the output keeps q's dtype."""
    q, k, v = _qkv(b=2, L=64, dtype=jnp.bfloat16)
    lengths = jnp.asarray([9, 64], jnp.int32)
    out = flash_decode_attention(q, k, v, lengths, block_k=32)
    assert out.dtype == jnp.bfloat16
    ref = _composed(*(t.astype(jnp.float32) for t in (q, k, v)), lengths)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)


def test_decode_kernel_under_jit_traced_lengths():
    q, k, v = _qkv(b=2, L=32)
    f = jax.jit(lambda q_, k_, v_, l_: flash_decode_attention(
        q_, k_, v_, l_, block_k=16))
    lengths = jnp.asarray([3, 30], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(f(q, k, v, lengths)),
        np.asarray(_composed(q, k, v, lengths)), rtol=1e-5, atol=1e-5)


def test_decode_kernel_rejects_multi_token_query():
    q, k, v = _qkv(b=1, L=16)
    q2 = jnp.concatenate([q, q], axis=2)                     # s_q == 2
    with pytest.raises(ValueError, match="single-token"):
        flash_decode_attention(q2, k, v, jnp.asarray([4], jnp.int32))


# --------------------------------------------------- model-level parity
def test_generate_greedy_parity_kernel_vs_composed():
    """The satellite contract: one-shot generate() routed through the
    flash-decode kernel (decode_impl='kernel', interpret mode on CPU) is
    BIT-IDENTICAL to the composed masked path for greedy decoding."""
    from nezha_tpu.models.generate import generate
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    kw = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
              hidden_size=64)
    composed = GPT2(GPT2Config(**kw, decode_impl="xla"))
    kernel = GPT2(GPT2Config(**kw, decode_impl="kernel"))
    variables = composed.init(jax.random.PRNGKey(0))
    prompt = np.asarray([[5, 17, 3, 42], [9, 1, 1, 7]], np.int32)
    a = np.asarray(generate(composed, variables, prompt, max_new_tokens=8,
                            cache_dtype=jnp.float32))
    b = np.asarray(generate(kernel, variables, prompt, max_new_tokens=8,
                            cache_dtype=jnp.float32))
    assert (a == b).all()


def test_decode_impl_env_escape_hatch(monkeypatch):
    """NEZHA_NO_DECODE_KERNEL=1 forces the composed path even when the
    config demands the kernel — the day-1 hardware escape hatch."""
    from nezha_tpu.models.gpt2 import GPT2Config, _decode_flash_ok

    cfg = GPT2Config(decode_impl="kernel")
    assert _decode_flash_ok(cfg)
    monkeypatch.setenv("NEZHA_NO_DECODE_KERNEL", "1")
    assert not _decode_flash_ok(cfg)
    monkeypatch.delenv("NEZHA_NO_DECODE_KERNEL")
    assert not _decode_flash_ok(GPT2Config(decode_impl="xla"))
    # auto follows the shared attn_impl resolution: composed on CPU.
    assert not _decode_flash_ok(GPT2Config(decode_impl="auto"))


# -------------------------------------------------------- benchmark CLI
def test_decode_attention_benchmark_cli(tmp_path):
    """benchmarks/decode_attention.py runs at tier-1 shapes (interpret
    mode) and writes schema-valid run-dir artifacts."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "benchmarks"))
    import decode_attention as bench

    run_dir = str(tmp_path / "bench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--batch-sizes", "2", "--max-lens", "32", "--num-heads", "2",
         "--head-dim", "8", "--skews", "full,mixed,one_active",
         "--dtype", "f32", "--iters", "2", "--warmup", "1",
         "--run-dir", run_dir]))
    assert rec["interpreted"] is True
    assert len(rec["configs"]) == 3
    assert all(c["kernel_ms"] > 0 and c["composed_ms"] > 0
               for c in rec["configs"])
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
