"""Fault-injection framework + end-to-end resilience (the PR-4 chaos
suite): plan grammar and determinism, branch-only no-op contract,
per-request error isolation in the serving loop (prefill faults, step
crashes with bounded retry, NaN/inf logit bursts), zero slot leaks under
a seeded 32-request chaos plan, graceful drain in both nezha-serve front
ends, the --fault-rate benchmark knob, and the fault-point registry pin
(tools/check_fault_points.py).

Everything serving runs the tiny CPU GPT-2 from test_serve.py's config
on a module-scoped engine — injected faults fire host-side (before
dispatch or on returned arrays), so a faulted engine's program set stays
valid for the next test."""

import dataclasses
import io
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nezha_tpu import faults, obs
from nezha_tpu.faults import FaultPlan, InjectedFault
from nezha_tpu.models.gpt2 import GPT2, GPT2Config
from nezha_tpu.serve import (
    Engine,
    FinishReason,
    Request,
    Scheduler,
    ServeConfig,
)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

CFG = dict(vocab_size=97, max_positions=64, num_layers=2, num_heads=4,
           hidden_size=64)
SCFG = ServeConfig(max_batch_size=3, max_len=48, max_prefill_len=8,
                   prefill_buckets=(4, 8), k_max=16, queue_capacity=4,
                   cache_dtype=jnp.float32)


@pytest.fixture(scope="module")
def model_and_vars():
    model = GPT2(GPT2Config(**CFG))
    return model, model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def engine(model_and_vars):
    model, variables = model_and_vars
    return Engine(model, variables, SCFG)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends plan-free — an installed plan is
    process-global state."""
    faults.clear()
    yield
    faults.clear()


def _drain(sched, max_iters=400):
    iters = sched.run_until_idle(max_iters=max_iters)
    assert not sched.has_work(), "scheduler did not drain"
    return iters


# ------------------------------------------------------------ plan layer
def test_plan_parse_grammar():
    p = FaultPlan.parse(
        "serve.prefill:error@3;a.b:delay=0.05x2;c.d:nan%0.5;e.f:inf@2x*")
    r = {rule.point: rule for rule in p.rules}
    assert r["serve.prefill"].action == "error"
    assert r["serve.prefill"].at == 3 and r["serve.prefill"].times == 1
    assert r["a.b"].delay_s == 0.05 and r["a.b"].times == 2
    assert r["c.d"].p == 0.5
    assert r["e.f"].at == 2 and r["e.f"].times == float("inf")


@pytest.mark.parametrize("bad", [
    "",                      # no rules
    "pointonly",             # no action
    "x:boom",                # unknown action
    "x:delay",               # delay without seconds
    "x:error=3",             # arg on a non-delay action
    "x:error%2",             # probability out of range
    "x:error@0",             # hits are 1-based
    "x:error@2%0.5",         # positional and probabilistic together
])
def test_plan_parse_rejects(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_point_noop_without_plan():
    faults.point("serve.prefill")          # must not raise
    x = np.ones((2, 2))
    assert faults.corrupt("serve.prefill.logits", x) is x
    assert not faults.enabled()


def test_point_fires_on_nth_hit_only():
    faults.install(FaultPlan.parse("p.q:error@2"))
    faults.point("p.q")                    # hit 1
    with pytest.raises(InjectedFault, match="p.q"):
        faults.point("p.q")                # hit 2
    faults.point("p.q")                    # hit 3: window closed
    assert faults.active().injected_counts == {"p.q": 1}
    assert faults.active().hit_counts == {"p.q": 3}


def test_delay_rule_sleeps():
    faults.install(FaultPlan.parse("p.q:delay=0.02"))
    t0 = time.monotonic()
    faults.point("p.q")
    assert time.monotonic() - t0 >= 0.015


def test_probabilistic_rules_are_seeded():
    def run_once():
        plan = FaultPlan.parse("p.q:error%0.5", seed=7)
        fired = []
        for i in range(100):
            try:
                faults.install(plan)
                faults.point("p.q")
                fired.append(False)
            except InjectedFault:
                fired.append(True)
        return fired

    a, b = run_once(), run_once()
    assert a == b                          # same seed, same schedule
    assert 20 < sum(a) < 80


def test_corrupt_poisons_seeded_row_copy():
    faults.install(FaultPlan.parse("p.q:nan@1;p.q:zero@2"))
    x = np.ones((4, 3), np.float32)
    y = faults.corrupt("p.q", x, rows=(1, 2))
    assert np.isnan(y).any() and not np.isnan(x).any()
    bad_rows = sorted(np.flatnonzero(np.isnan(y).any(axis=1)))
    assert bad_rows in ([1], [2])          # one victim, from `rows`
    z = faults.corrupt("p.q", x, rows=(0,))
    assert (z[0] == 0).all() and (z[1:] == 1).all()
    # jnp in -> jnp out
    w = faults.corrupt("p.q", jnp.ones((2, 2)))       # no rule left: as-is
    assert w.shape == (2, 2)


def test_corrupt_with_empty_rows_is_noop():
    faults.install(FaultPlan.parse("p.q:nan@1"))
    x = np.ones((2, 2))
    assert faults.corrupt("p.q", x, rows=()) is x
    # nothing was poisoned, so nothing may be ACCOUNTED as injected —
    # injected_counts reports chaos that happened, not rules that fired
    assert faults.active().injected_counts == {}


def test_discarded_corrupt_rule_at_control_point_not_counted():
    """A corruption rule matching a plain point() site injects nothing
    (there is no tensor) and must not be counted as an injection."""
    faults.install(FaultPlan.parse("p.q:nan@1x*"))
    for _ in range(5):
        faults.point("p.q")
    assert faults.active().injected_counts == {}
    assert faults.active().hit_counts == {"p.q": 5}


def test_install_from_env(monkeypatch):
    monkeypatch.setenv("NEZHA_FAULT_PLAN", "a.b:error@4")
    monkeypatch.setenv("NEZHA_FAULT_SEED", "11")
    plan = faults.install_from_env()
    assert plan is faults.active()
    assert plan.seed == 11 and plan.rules[0].at == 4
    # unset/empty leaves the installed plan untouched
    monkeypatch.setenv("NEZHA_FAULT_PLAN", "")
    assert faults.install_from_env() is None
    assert faults.active() is plan


# ----------------------------------------------- serving: error isolation
def test_prefill_fault_retires_only_victim(engine):
    faults.install(FaultPlan.parse("serve.prefill:error@2"))
    sched = Scheduler(engine)
    for i in range(3):
        sched.submit(Request(prompt=[1 + i, 2, 3], max_new_tokens=3,
                             request_id=f"r{i}"))
    _drain(sched)
    res = sched.results
    # Admission order: r0 (prefill hit 1), r1 (hit 2 -> fault), r2.
    assert res["r1"].finish_reason == FinishReason.ERROR
    assert res["r1"].tokens == [] and res["r1"].ttft_s is None
    assert "InjectedFault" in res["r1"].error
    assert res["r0"].finish_reason == "length"
    assert res["r2"].finish_reason == "length"
    assert engine.pool.num_free == SCFG.max_batch_size   # zero slot leaks


def test_genuine_prefill_exception_is_isolated(engine, monkeypatch):
    """Not just injected faults: any runtime exception out of prefill
    (the XLA-error case the old `# submit() pre-validates` comment
    ignored) retires only that request."""
    real = engine.prefill
    calls = {"n": 0}

    def flaky(slot, tokens, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("XLA went sideways")
        return real(slot, tokens, **kw)

    monkeypatch.setattr(engine, "prefill", flaky)
    sched = Scheduler(engine)
    a = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=2))
    b = sched.submit(Request(prompt=[7, 7], max_new_tokens=2))
    _drain(sched)
    assert sched.results[a].finish_reason == FinishReason.ERROR
    assert "XLA went sideways" in sched.results[a].error
    assert sched.results[b].finish_reason == "length"
    assert engine.pool.num_free == SCFG.max_batch_size


def test_step_crash_bounded_retry(engine):
    """One mid-stream engine.step crash is absorbed by a single backoff
    retry (serving continues, nobody is retired); two consecutive
    crashes surface."""
    faults.install(FaultPlan.parse("serve.step:error@2"))
    sched = Scheduler(engine)
    a = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=4))
    _drain(sched)
    assert sched.results[a].finish_reason == "length"
    assert len(sched.results[a].tokens) == 4
    assert faults.active().injected_counts == {"serve.step": 1}
    assert engine.pool.num_free == SCFG.max_batch_size

    faults.install(FaultPlan.parse("serve.step:error@1x2"))
    sched = Scheduler(engine)
    sched.submit(Request(prompt=[5, 17], max_new_tokens=2))
    with pytest.raises(InjectedFault):
        sched.step()
    # The failure surfaced but nothing leaked: clearing the plan lets
    # the SAME scheduler finish the in-flight request.
    faults.clear()
    _drain(sched)
    assert engine.pool.num_free == SCFG.max_batch_size


def test_nan_prefill_burst_retires_before_first_token(engine):
    faults.install(FaultPlan.parse("serve.prefill.logits:nan@1"))
    sched = Scheduler(engine)
    v = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=4,
                             request_id="victim"))
    w = sched.submit(Request(prompt=[7, 7], max_new_tokens=4,
                             request_id="witness"))
    _drain(sched)
    res = sched.results
    assert res[v].finish_reason == FinishReason.ERROR
    assert res[v].tokens == [] and res[v].error == "non-finite logits"
    assert res[w].finish_reason == "length"
    assert len(res[w].tokens) == 4
    assert engine.pool.num_free == SCFG.max_batch_size


def test_nan_midstream_burst_keeps_neighbors_decoding(engine):
    """A NaN burst on step 2's logits retires the victim with its
    partial output while the other row decodes to completion — and the
    freed slot is reusable (the next occupant's prefill overwrites the
    poisoned logits row)."""
    faults.install(FaultPlan.parse("serve.step.logits:nan@2"))
    sched = Scheduler(engine)
    a = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=6))
    _drain(sched)
    res = sched.results[a]
    assert res.finish_reason == FinishReason.ERROR
    assert len(res.tokens) == 2            # poisoned after step 2
    assert engine.pool.num_free == SCFG.max_batch_size
    faults.clear()
    b = sched.submit(Request(prompt=[5, 17, 3], max_new_tokens=6))
    _drain(sched)
    assert sched.results[b].finish_reason == "length"
    assert len(sched.results[b].tokens) == 6


# -------------------------------------------------- the chaos acceptance
def test_chaos_open_loop_32_requests(model_and_vars, tmp_path):
    """The PR acceptance scenario: a seeded plan injects prefill
    exceptions, one mid-stream engine.step crash, and NaN logit bursts
    across a 32-request open-loop run. The server retires every affected
    request with finish_reason "error", keeps serving the rest to
    completion, leaks zero slots, keeps the program set frozen, and the
    run's artifacts carry the pinned error/retry/fault counters."""
    model, variables = model_and_vars
    run_dir = str(tmp_path / "chaos")
    obs.start_run(run_dir, meta={"kind": "chaos_test"})
    try:
        engine = Engine(model, variables, SCFG)
        sched = Scheduler(engine)
        faults.install(FaultPlan.parse(
            "serve.prefill:error@5;serve.prefill:error@19;"
            "serve.step:error@9;"
            "serve.prefill.logits:nan@11;serve.step.logits:nan@21",
            seed=3))
        issued = 0
        while issued < 32 or sched.has_work():
            while issued < 32 and sched.queue_depth < SCFG.queue_capacity:
                # Alternate prompt lengths 3/6 so BOTH prefill buckets
                # (4, 8) compile and the frozen-program assertion below
                # covers the full set.
                n = 3 if issued % 2 == 0 else 6
                sched.submit(Request(
                    prompt=[(3 * issued + j + 1) % 97 for j in range(n)],
                    max_new_tokens=6, request_id=f"c{issued}"))
                issued += 1
            sched.step()
        plan = faults.active()
        results = [sched.results[f"c{i}"] for i in range(32)]
        errored = [r for r in results if r.finish_reason == "error"]
        clean = [r for r in results if r.finish_reason != "error"]
        # Prefill errors and the prefill NaN burst each claim exactly one
        # victim; the step NaN burst claims one unless its seeded victim
        # retired on the same step; the step crash is absorbed by the
        # bounded retry and claims nobody.
        assert plan.injected_counts["serve.prefill"] == 2
        assert plan.injected_counts["serve.prefill.logits"] == 1
        assert plan.injected_counts["serve.step"] == 1
        assert 3 <= len(errored) <= 4
        assert all(r.error for r in errored)
        # Everyone else decoded to completion next to the chaos.
        assert all(r.finish_reason == "length" for r in clean)
        assert all(len(r.tokens) == 6 for r in clean)
        # Zero slot leaks, frozen program set.
        assert engine.pool.num_free == SCFG.max_batch_size
        stats = engine.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(SCFG.prefill_buckets)
        assert obs.counter("serve.step_retries_total").value == 1
        assert obs.counter("serve.errors_total").value == len(errored)
        assert obs.counter("faults.injected_total").value == \
            plan.num_injected
    finally:
        faults.clear()
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    from nezha_tpu.obs.report import render_report
    report = render_report(run_dir)
    assert "errors:" in report and "faults injected" in report


def test_chaos_at_decode_horizon_4(model_and_vars, tmp_path):
    """The chaos acceptance re-run at decode_horizon=4 (ISSUE 5): with
    the health mask CARRIED ACROSS THE SCAN, a NaN burst between blocks
    freezes only its victim from the next block's first step (pre-burst
    tokens delivered, overshoot dropped on device), injected prefill
    errors stay request-scoped, neighbors sharing the victim's blocks
    decode to completion, zero slots leak, and the frozen program set +
    pinned telemetry schema survive block decoding."""
    model, variables = model_and_vars
    cfg = dataclasses.replace(SCFG, decode_horizon=4)
    run_dir = str(tmp_path / "chaos_h4")
    obs.start_run(run_dir, meta={"kind": "chaos_test_h4"})
    try:
        engine = Engine(model, variables, cfg)
        sched = Scheduler(engine)
        faults.install(FaultPlan.parse(
            "serve.prefill:error@5;serve.prefill.logits:nan@11;"
            "serve.step.logits:nan@3", seed=3))
        issued = 0
        while issued < 32 or sched.has_work():
            while issued < 32 and sched.queue_depth < cfg.queue_capacity:
                n = 3 if issued % 2 == 0 else 6   # both prefill buckets
                sched.submit(Request(
                    prompt=[(3 * issued + j + 1) % 97 for j in range(n)],
                    max_new_tokens=6, request_id=f"c{issued}"))
                issued += 1
            sched.step()
        plan = faults.active()
        results = [sched.results[f"c{i}"] for i in range(32)]
        errored = [r for r in results if r.finish_reason == "error"]
        clean = [r for r in results if r.finish_reason != "error"]
        # The prefill error and prefill NaN each claim exactly one
        # victim; the between-blocks NaN burst claims one more UNLESS
        # its seeded victim row retired on that very block (its slot
        # then holds no request when the poisoned carry is noticed).
        assert plan.injected_counts["serve.prefill"] == 1
        assert plan.injected_counts["serve.prefill.logits"] == 1
        assert plan.injected_counts["serve.step.logits"] == 1
        assert 2 <= len(errored) <= 3
        assert all(r.error for r in errored)
        # A step.logits victim keeps its pre-burst blocks: whatever it
        # has is a clean prefix (< 6, or it would have finished clean).
        for r in errored:
            assert len(r.tokens) < 6
        # Everyone else decoded to completion next to the chaos —
        # including rows that shared scan steps with frozen victims.
        assert all(r.finish_reason == "length" for r in clean)
        assert all(len(r.tokens) == 6 for r in clean)
        # Zero slot leaks, frozen program set (horizon baked into the
        # one step program — still 1 + len(prefill_buckets)).
        assert engine.pool.num_free == cfg.max_batch_size
        stats = engine.compile_stats()
        assert stats["entries"] == stats["misses"] == \
            1 + len(cfg.prefill_buckets)
        assert obs.counter("serve.errors_total").value == len(errored)
    finally:
        faults.clear()
        obs.end_run()
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []


# -------------------------------------------------------- graceful drain
def _stdio_server(tmp_args=()):
    """Start nezha-serve stdio mode on a background thread against a
    pipe; -> (write_fn, drain_event, stdout_buffer, thread, rc_box)."""
    from nezha_tpu.cli.serve import build_parser, run as serve_run

    r_fd, w_fd = os.pipe()
    stdin = os.fdopen(r_fd, "r")
    w = os.fdopen(w_fd, "w")
    stdout = io.StringIO()
    drain = threading.Event()
    rc = {}
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8",
         "--platform", "cpu", *tmp_args])

    def serve():
        rc["rc"] = serve_run(args, stdin=stdin, stdout=stdout,
                             drain_event=drain)

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    def write(obj):
        w.write(json.dumps(obj) + "\n")
        w.flush()

    return write, drain, stdout, t, rc


def _events(stdout):
    return [json.loads(ln) for ln in stdout.getvalue().splitlines()]


def test_stdio_drain_finishes_in_flight():
    """Drain with budget: the in-flight request finishes, the final
    flushed event is {"event": "drain"}, and the server exits 0 without
    stdin ever closing."""
    write, drain, stdout, t, rc = _stdio_server(["--drain-timeout", "30"])
    write({"id": "a", "prompt_tokens": [5, 17, 3], "max_new_tokens": 24})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if any(e["event"] == "token" for e in _events(stdout)):
            break
        time.sleep(0.01)
    drain.set()
    t.join(timeout=60)
    assert not t.is_alive() and rc["rc"] == 0
    events = _events(stdout)
    done = [e for e in events if e["event"] == "done"]
    assert [e["id"] for e in done] == ["a"]
    assert done[0]["finish_reason"] == "length"
    assert len(done[0]["tokens"]) == 24    # drain let it FINISH
    assert events[-1]["event"] == "drain"
    assert events[-1]["cancelled"] == 0


def test_stdio_drain_deadline_cancels_stragglers(monkeypatch):
    """Zero drain budget: in-flight work is cancelled at the cutoff with
    finish_reason "deadline" (tokens so far preserved), and the drain
    event reports the cancellation. The decode loop is slowed by an
    env-installed delay fault plan — which also exercises the
    NEZHA_FAULT_PLAN wiring through the real serve entry point."""
    monkeypatch.setenv("NEZHA_FAULT_PLAN", "serve.step:delay=0.05x*")
    write, drain, stdout, t, rc = _stdio_server(
        ["--drain-timeout", "0", "--max-new-tokens", "40"])
    write({"id": "a", "prompt_tokens": [5, 17, 3],
           "max_new_tokens": 40})   # 40 x 50ms: cannot finish by the cutoff
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if any(e["event"] == "token" for e in _events(stdout)):
            break
        time.sleep(0.01)
    drain.set()
    t.join(timeout=60)
    assert not t.is_alive() and rc["rc"] == 0
    events = _events(stdout)
    done = [e for e in events if e["event"] == "done"]
    assert done and done[0]["finish_reason"] == "deadline"
    assert events[-1]["event"] == "drain"
    assert events[-1]["cancelled"] == 1


def test_stdio_drain_answers_request_awaiting_queue_room(monkeypatch):
    """A request already read off stdin but not yet admitted when the
    drain hits (queue full, reader parked waiting for room) must be
    answered with a "draining" error event — the stdio analogue of
    HTTP's 503 — never dropped silently."""
    monkeypatch.setenv("NEZHA_FAULT_PLAN", "serve.step:delay=0.05x*")
    write, drain, stdout, t, rc = _stdio_server(
        ["--max-batch-size", "1", "--queue-capacity", "1",
         "--drain-timeout", "30", "--max-new-tokens", "30"])
    # r0 takes the only slot, r1 the only queue seat, r2 waits for room.
    for i in range(3):
        write({"id": f"r{i}", "prompt_tokens": [5, 17],
               "max_new_tokens": 30})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if any(e["event"] == "token" for e in _events(stdout)):
            break
        time.sleep(0.01)
    drain.set()
    t.join(timeout=120)
    assert not t.is_alive() and rc["rc"] == 0
    events = _events(stdout)
    assert events[-1]["event"] == "drain"
    # every request got SOME answer: done (finished/cancelled in the
    # drain window) or the draining error — none vanished
    answered = {e.get("id") for e in events
                if e["event"] in ("done", "error")}
    assert answered >= {"r0", "r1", "r2"}
    drained_away = [e for e in events if e["event"] == "error"
                    and e.get("error") == "draining"]
    assert drained_away, "waiting request was dropped without an answer"


def test_serve_run_installs_signal_handlers(monkeypatch):
    """run() wires SIGTERM and SIGINT to the drain event (and restores
    the old handlers on exit) — the real-signal path of the drain tests
    above."""
    import signal as signal_mod

    from nezha_tpu.cli.serve import build_parser, run as serve_run

    installed = {}
    restored = {}
    real_signal = signal_mod.signal

    def fake_signal(sig, handler):
        (restored if sig in installed else installed)[sig] = handler
        return signal_mod.SIG_DFL

    monkeypatch.setattr(signal_mod, "signal", fake_signal)
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "1", "--max-len", "16", "--max-prefill-len", "8",
         "--platform", "cpu"])
    assert serve_run(args, stdin=io.StringIO(""),
                     stdout=io.StringIO()) == 0
    assert set(installed) == {signal_mod.SIGTERM, signal_mod.SIGINT}
    assert set(restored) == {signal_mod.SIGTERM, signal_mod.SIGINT}
    # the installed handler sets the drain path, not KeyboardInterrupt
    handler = installed[signal_mod.SIGTERM]
    handler(signal_mod.SIGTERM, None)      # must not raise
    monkeypatch.setattr(signal_mod, "signal", real_signal)


def test_http_drain_closes_admission_and_finishes(tmp_path, monkeypatch):
    """HTTP drain: /healthz flips to 503 "draining", new POSTs get 503,
    the in-flight POST completes, and the server shuts itself down. A
    per-step delay fault keeps the in-flight request decoding long
    enough (~50ms x 48 tokens) for the draining window to be observable
    from outside."""
    import urllib.error
    import urllib.request

    from nezha_tpu.cli.serve import build_parser, run as serve_run

    # 60 tokens x 80ms: a ~5s draining window, wide enough that the
    # healthz poll below observes it even on a loaded machine (48 x
    # 50ms flaked under CPU contention — every poll in the window can
    # time out behind the GIL).
    monkeypatch.setenv("NEZHA_FAULT_PLAN", "serve.step:delay=0.08x*")
    ready = {}
    ready_evt = threading.Event()

    def ready_cb(server):
        ready["port"] = server.server_address[1]
        ready_evt.set()

    drain = threading.Event()
    rc = {}
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "64", "--max-prefill-len", "8",
         "--max-new-tokens", "60", "--platform", "cpu",
         "--http", "0", "--drain-timeout", "30"])
    t = threading.Thread(
        target=lambda: rc.update(rc=serve_run(args, ready_cb=ready_cb,
                                              drain_event=drain)),
        daemon=True)
    t.start()
    assert ready_evt.wait(timeout=120)
    base = f"http://127.0.0.1:{ready['port']}"

    def post(payload, timeout=60):
        req = urllib.request.Request(
            f"{base}/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    result = {}
    inflight = threading.Thread(
        target=lambda: result.update(post(
            {"id": "slow", "prompt_tokens": [5, 17, 3],
             "max_new_tokens": 60})),
        daemon=True)
    inflight.start()
    # wait until the slow request is actually occupying a slot
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            if json.loads(r.read())["active"] > 0:
                break
        time.sleep(0.01)
    drain.set()
    # healthz flips to 503 draining while the in-flight request finishes.
    # Transient poll errors (a urlopen timing out behind the scheduler
    # lock, a connection reset mid-shutdown) are retried, not treated as
    # "server gone" — only the serve thread actually exiting ends the
    # poll early.
    saw_draining = False
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not saw_draining:
        if not t.is_alive():
            break
        try:
            with urllib.request.urlopen(f"{base}/healthz",
                                        timeout=2) as r:
                pass
        except urllib.error.HTTPError as e:
            body = json.loads(e.read())
            saw_draining = (e.code == 503
                            and body["status"] in ("draining",
                                                   "decode loop stopped"))
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.05)           # transient; retry
        time.sleep(0.01)
    # a NEW request is refused while draining (unless shutdown already
    # completed, in which case the connection itself fails)
    try:
        post({"id": "late", "prompt_tokens": [1, 2], "max_new_tokens": 2},
             timeout=10)
        refused = False
    except urllib.error.HTTPError as e:
        refused = e.code == 503
        # A 503 on POST /generate mid-drain is the same admission-
        # closed observation the healthz poll hunts for — count it, in
        # case contention made every poll in the window time out.
        saw_draining = saw_draining or e.code == 503
    except (urllib.error.URLError, ConnectionError, OSError):
        refused = True
    assert refused
    inflight.join(timeout=120)
    assert result.get("finish_reason") == "length"
    assert len(result["tokens"]) == 60     # drain let it finish
    t.join(timeout=120)
    assert not t.is_alive() and rc["rc"] == 0
    assert saw_draining


# ------------------------------------------------- benchmark + registry
def test_serving_benchmark_fault_rate(tmp_path):
    sys.path.insert(0, os.path.join(_ROOT, "benchmarks"))
    import serving as bench

    run_dir = str(tmp_path / "bench")
    rec = bench.run(bench.build_parser().parse_args(
        ["--mode", "open", "--rate", "100", "--requests", "12",
         "--prompt-len", "4", "--max-new-tokens", "4",
         "--max-batch-size", "2", "--max-len", "16",
         "--max-prefill-len", "8", "--fault-rate", "0.25",
         "--seed", "3", "--run-dir", run_dir]))
    assert rec["faults"]["rate"] == 0.25
    assert rec["faults"]["injected"] > 0
    assert rec["faults"]["errored"] > 0
    assert rec["finished"] + rec["dropped_queue_full"] == 12
    assert faults.active() is None         # plan restored after the run
    from check_telemetry_schema import check_run_dir
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        counters = json.load(f)["counters"]
    assert counters["serve.errors_total"] == rec["faults"]["errored"]
    assert counters["faults.injected_total"] > 0


def test_fault_point_registry_pinned():
    """Every registered faults.point()/corrupt() name is unique,
    documented in the RUNBOOK, covered by a test, and pinned in the
    validator's EXPECTED_POINTS — and the validator actually sees the
    full set, including the multi-replica points (router.route /
    router.probe / supervisor.spawn / replica.exec), the paged-KV
    bind point (serve.kv.bind), and the migration points
    (router.migrate / replica.kv_export / replica.kv_install), the
    speculative verify point (serve.spec.verify), the host-tier
    promotion point (serve.kv.promote), the train->serve
    resharding point (serve.reshard), the fleet KV reuse points
    (router.affinity / replica.kv_pull), the multi-tenant
    scheduling points (scheduler.preempt / supervisor.scale), and the
    sequence-sharded prefill point (serve.prefill.seq)."""
    from check_fault_points import EXPECTED_POINTS, check, find_points

    assert check(_ROOT) == []
    assert set(find_points(_ROOT)) == {
        "serve.prefill", "serve.prefill.logits",
        "serve.step", "serve.step.logits",
        "checkpoint.save", "dist.join",
        "router.route", "router.probe",
        "supervisor.spawn", "replica.exec",
        "serve.kv.bind", "serve.kv.promote",
        "router.migrate", "replica.kv_export", "replica.kv_install",
        "serve.spec.verify",
        "serve.reshard",
        "router.affinity", "replica.kv_pull",
        "scheduler.preempt", "supervisor.scale",
        "serve.prefill.seq",
    }
    assert set(find_points(_ROOT)) == set(EXPECTED_POINTS)
