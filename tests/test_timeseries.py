"""Windowed fleet telemetry (ISSUE 16): rolling time-series windows,
the SLO/error-budget engine, the Prometheus /metrics exposition, and
the anomaly watchdog.

Layers under test, bottom up: the mergeable log-bucket sketch (merged
replica sketches report the SAME quantile bounds as one union-stream
sketch — the exactness pin fleet roll-ups rely on), the bucket ring
(rotation under concurrent writers loses nothing), the registry window
tap (disabled mode records nothing), the fleet merge (registry-identity
dedupe — thread and process backends must report identical fleet
totals, the PR 12 /stats over-count fix), the /metrics exposition
(render -> parse roundtrip, pinned against the stdlib-only schema
module's constants), the SLO grammar + hand-computed burn-rate trace,
the watchdog rules (rising-edge typed events into events.jsonl,
schema-valid), the CLI surfaces (nezha-serve --slo, nezha-telemetry
--slo, nezha-top), and the end-to-end acceptance: a multi-replica
fleet under load serves a fleet-rolled /metrics whose windowed TTFT
matches the run-dir artifacts, and a fault-injected latency regression
trips the watchdog.
"""

import json
import math
import os
import sys
import threading
import time
import urllib.request

import pytest

import jax

from nezha_tpu import faults, obs
from nezha_tpu.obs import timeseries as ts
from nezha_tpu.obs.slo import (SLOTracker, evaluate_slo, parse_slo,
                               parse_slo_args)
from nezha_tpu.obs.watchdog import Watchdog, WatchdogConfig, WatchdogThread
from nezha_tpu.serve.router import Router, register_router_instruments
from nezha_tpu.serve.scheduler import register_serve_instruments
from nezha_tpu.serve.supervisor import (ProcessBackend, RouterConfig,
                                        Supervisor, ThreadBackend)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
from check_telemetry_schema import (EVENT_KINDS, check_events_jsonl,  # noqa: E402
                                    check_metrics_exposition,
                                    check_run_dir)


@pytest.fixture(autouse=True)
def _clean_obs():
    faults.clear()
    obs.end_run()
    obs.REGISTRY.reset()
    yield
    faults.clear()
    obs.end_run()
    obs.REGISTRY.reset()


# ----------------------------------------------------------- LogSketch
def test_sketch_quantile_bounds():
    """Every reported quantile is within a gamma factor of the true
    value (the DDSketch relative-error guarantee), clamped into the
    exact observed [min, max]."""
    sk = ts.LogSketch()
    values = [0.001 * (i + 1) for i in range(1000)]
    for v in values:
        sk.observe(v)
    s = sk.summary()
    assert s["count"] == 1000
    assert s["min"] == pytest.approx(0.001)
    assert s["max"] == pytest.approx(1.0)
    assert s["sum"] == pytest.approx(sum(values))
    for q, true in ((50, 0.5), (90, 0.9), (99, 0.99)):
        got = sk.quantile(q)
        assert true / ts.DEFAULT_GAMMA <= got <= true * ts.DEFAULT_GAMMA, (
            q, got, true)


def test_sketch_zero_and_negative_bucket():
    sk = ts.LogSketch()
    for v in (0.0, -1.5, 0.25):
        sk.observe(v)
    s = sk.summary()
    assert s["count"] == 3
    assert s["min"] == -1.5 and s["max"] == 0.25
    # p50 falls in the zero/negative mass -> reported as the floor 0.0
    # clamped to min
    assert sk.quantile(50) <= 0.25


def test_sketch_merge_exactness():
    """THE fleet roll-up pin: merging per-replica sketches yields
    byte-identical buckets — and therefore IDENTICAL quantile bounds —
    to one sketch fed the union stream. (``sum``/``mean`` may differ by
    float addition order; count/min/max/quantiles must be exact.)"""
    import random
    rng = random.Random(7)
    streams = [[rng.lognormvariate(-3.0, 1.0) for _ in range(400)]
               for _ in range(3)]
    parts = []
    union = ts.LogSketch()
    for stream in streams:
        p = ts.LogSketch()
        for v in stream:
            p.observe(v)
            union.observe(v)
        parts.append(p)
    merged = ts.LogSketch()
    for p in parts:
        merged.merge(p)
    assert merged.buckets == union.buckets
    assert merged.zero == union.zero
    ms, us = merged.summary(), union.summary()
    for key in ("count", "min", "max", "p50", "p90", "p99"):
        assert ms[key] == us[key], key
    assert math.isclose(ms["sum"], us["sum"], rel_tol=1e-9)


def test_sketch_serialization_roundtrip():
    sk = ts.LogSketch()
    for v in (0.01, 0.5, 0.5, 3.0, 0.0):
        sk.observe(v)
    d = json.loads(json.dumps(sk.to_dict()))   # survives JSON transport
    back = ts.LogSketch.from_dict(d)
    assert back.buckets == sk.buckets
    assert back.summary() == sk.summary()


# --------------------------------------------------------- WindowStore
def _fake_clock(start=1000.0):
    state = {"t": start}

    def clock():
        return state["t"]

    return state, clock


def test_window_rotation_and_rates():
    state, clock = _fake_clock()
    store = ts.WindowStore(interval_s=10.0, retention_s=300.0,
                           clock=clock)
    # 3 buckets: 5 incs in the first, 3 in the second, 2 in the third.
    for n, _ in ((5, 0), (3, 1), (2, 2)):
        for _ in range(n):
            store.record_counter("serve.admitted_total", 1)
        state["t"] += 10.0
    state["t"] -= 10.0        # stay inside the third bucket
    v10 = store.view(10.0)
    assert v10["counters"]["serve.admitted_total"]["delta"] == 2
    assert v10["counters"]["serve.admitted_total"]["rate"] == \
        pytest.approx(0.2)
    v30 = store.view(30.0)
    assert v30["buckets"] == 3
    assert v30["counters"]["serve.admitted_total"]["delta"] == 10
    assert v30["counters"]["serve.admitted_total"]["rate"] == \
        pytest.approx(10 / 30)
    # skip drops the NEWEST buckets (the watchdog's trailing baseline).
    v_base = store.view(30.0, skip=1)
    assert v_base["counters"]["serve.admitted_total"]["delta"] == 8


def test_window_gauge_and_histogram_rollup():
    state, clock = _fake_clock()
    store = ts.WindowStore(interval_s=10.0, retention_s=60.0,
                           clock=clock)
    store.record_gauge("serve.queue_depth", 4)
    store.record_histogram("serve.ttft_s", 0.02)
    state["t"] += 10.0
    store.record_gauge("serve.queue_depth", 9)
    store.record_gauge("serve.queue_depth", 1)
    store.record_histogram("serve.ttft_s", 0.08)
    view = store.view(60.0)
    g = view["gauges"]["serve.queue_depth"]
    assert g == {"last": 1, "min": 1, "max": 9}
    h = view["histograms"]["serve.ttft_s"]
    assert h["count"] == 2
    assert h["min"] == pytest.approx(0.02)
    assert h["max"] == pytest.approx(0.08)
    assert "sketch" in h    # mergeable transport form rides in the view


def test_window_retention_bounds_memory():
    state, clock = _fake_clock()
    store = ts.WindowStore(interval_s=1.0, retention_s=5.0, clock=clock)
    for i in range(50):
        store.record_counter("c", 1)
        state["t"] += 1.0
    assert len(store._buckets) == 5           # ring stayed bounded
    assert store.view(300.0)["counters"]["c"]["delta"] == 5


def test_window_concurrent_writers_lose_nothing():
    """Satellite 3: writer threads hammer the store while the clock
    advances under them (bucket rotation mid-write). Every increment
    must land in SOME retained bucket — the one lock serializes
    recording and rotation."""
    state, clock = _fake_clock()
    # Retention far exceeds the simulated time span: nothing ages out,
    # so conservation is exact.
    store = ts.WindowStore(interval_s=1.0, retention_s=10_000.0,
                           clock=clock)
    N, W = 2000, 4
    stop = threading.Event()

    def rotator():
        while not stop.is_set():
            state["t"] += 0.25            # rotates every few writes
            time.sleep(0.0002)

    def writer(k):
        for _ in range(N):
            store.record_counter("hits", 1)
            store.record_histogram("lat", 0.01)

    rot = threading.Thread(target=rotator, daemon=True)
    rot.start()
    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(W)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    stop.set()
    rot.join(timeout=10)
    assert not any(t.is_alive() for t in threads)
    view = store.view(10_000.0)
    assert view["counters"]["hits"]["delta"] == N * W
    assert view["histograms"]["lat"]["count"] == N * W


# ------------------------------------------------- registry window tap
def test_registry_tap_and_disabled_noop():
    obs.enable()
    try:
        store = ts.install_windows(interval_s=10.0)
        obs.counter("serve.admitted_total").inc(3)
        obs.gauge("serve.queue_depth").set(2)
        obs.histogram("serve.ttft_s").observe(0.05)
        view = obs.windows(60.0)
        assert view["counters"]["serve.admitted_total"]["delta"] == 3
        assert view["gauges"]["serve.queue_depth"]["last"] == 2
        assert view["histograms"]["serve.ttft_s"]["count"] == 1
        # Disabled: instrument writes don't reach the store either.
        obs.disable()
        obs.counter("serve.admitted_total").inc(100)
        obs.histogram("serve.ttft_s").observe(9.0)
        obs.enable()
        view = obs.windows(60.0)
        assert view["counters"]["serve.admitted_total"]["delta"] == 3
        assert view["histograms"]["serve.ttft_s"]["count"] == 1
        assert store is ts.current_windows()
    finally:
        ts.uninstall_windows()
        obs.disable()


def test_windows_view_without_store_is_empty_shape():
    view = obs.windows(60.0)
    assert view["buckets"] == 0
    assert view["counters"] == {} and view["histograms"] == {}


# ---------------------------------------------------------- fleet merge
def _payload_with(registry_id, counters=(), gauges=(), hist=()):
    state, clock = _fake_clock()
    store = ts.WindowStore(interval_s=10.0, clock=clock)
    for name, n in counters:
        store.record_counter(name, n)
    for name, v in gauges:
        store.record_gauge(name, v)
    for name, vals in hist:
        for v in vals:
            store.record_histogram(name, v)
    return {"window_schema_version": 1, "ts": clock(),
            "registry_id": registry_id,
            "windows": {"60s": store.view(60.0)}}


def test_merge_dedupes_by_registry_identity():
    """The satellite-1 pin at the merge layer: two members backed by
    the SAME registry (thread backend) contribute once; distinct
    registries (process backend) sum."""
    shared = _payload_with("reg-a",
                           counters=[("serve.admitted_total", 5)],
                           gauges=[("serve.queue_depth", 3)])
    merged = ts.merge_window_payloads([shared, shared])
    assert merged["members"] == 2 and merged["deduped"] == 1
    view = merged["windows"]["60s"]
    assert view["counters"]["serve.admitted_total"]["delta"] == 5

    other = _payload_with("reg-b",
                          counters=[("serve.admitted_total", 7)],
                          gauges=[("serve.queue_depth", 2)])
    merged = ts.merge_window_payloads([shared, other, shared])
    assert merged["members"] == 3 and merged["deduped"] == 1
    view = merged["windows"]["60s"]
    assert view["counters"]["serve.admitted_total"]["delta"] == 12
    # Fleet gauge: "last" sums (total queued across the fleet),
    # min/max envelope.
    assert view["gauges"]["serve.queue_depth"]["last"] == 5
    assert view["gauges"]["serve.queue_depth"]["max"] == 3


def test_merge_sketches_fleet_exact():
    """Fleet histogram quantiles come from MERGED sketches, not from
    averaging member summaries — identical to a union-stream sketch."""
    a_vals = [0.01 * (i + 1) for i in range(100)]
    b_vals = [0.5 + 0.01 * i for i in range(100)]
    a = _payload_with("a", hist=[("serve.ttft_s", a_vals)])
    b = _payload_with("b", hist=[("serve.ttft_s", b_vals)])
    merged = ts.merge_window_payloads([a, b])
    union = ts.LogSketch()
    for v in a_vals + b_vals:
        union.observe(v)
    got = merged["windows"]["60s"]["histograms"]["serve.ttft_s"]
    want = union.summary()
    for key in ("count", "min", "max", "p50", "p90", "p99"):
        assert got[key] == want[key], key


# ------------------------------------------------- /metrics exposition
def test_prometheus_render_parse_roundtrip():
    obs.enable()
    try:
        ts.install_windows(interval_s=10.0)
        obs.counter("serve.admitted_total").inc(5)
        obs.gauge("serve.queue_depth").set(4)
        for i in range(50):
            obs.histogram("serve.ttft_s").observe(0.01 + 0.001 * i)
        text = ts.render_prometheus(obs.stats_snapshot(),
                                    ts.windows_payload())
    finally:
        ts.uninstall_windows()
        obs.disable()
    assert check_metrics_exposition(text) == []
    samples = ts.parse_prometheus(text)
    # Cumulative samples: unlabeled.
    assert ts.metric_value(samples, "nezha_serve_admitted_total") == 5
    assert ts.metric_value(samples, "nezha_serve_queue_depth") == 4
    # Windowed samples: every pinned window label renders.
    for w in ts.WINDOW_LABELS:
        assert ts.metric_value(samples, "nezha_serve_admitted_total_rate",
                               window=w) is not None, w
    assert ts.metric_value(samples, "nezha_serve_queue_depth_last",
                           window="60s") == 4
    p99 = ts.metric_value(samples, "nezha_serve_ttft_s",
                          window="60s", quantile="p99")
    assert p99 == pytest.approx(0.059, rel=ts.DEFAULT_GAMMA - 1 + 0.01)
    assert ts.metric_value(samples, "nezha_serve_ttft_s_count",
                           window="60s") == 50


def test_exposition_constants_pinned_against_schema_module():
    """The stdlib-only schema module duplicates the exposition
    constants (the tools shim can't import timeseries without jax);
    this is the unit pin that they never drift apart."""
    from nezha_tpu.analysis import telemetry_schema as sch
    assert sch.EXPOSITION_PREFIX == ts.EXPOSITION_PREFIX
    assert tuple(sch.EXPOSITION_WINDOW_LABELS) == tuple(ts.WINDOW_LABELS)
    assert tuple(sch.EXPOSITION_QUANTILE_LABELS) == \
        tuple(ts.QUANTILE_LABELS)
    assert set(sch.EVENT_KINDS) == set(EVENT_KINDS)


# ----------------------------------------------------------------- SLO
def test_slo_parse_roundtrip_and_errors():
    cfg = parse_slo("serve.ttft_s p99 < 0.5 over 60s objective 0.99")
    assert cfg.metric == "serve.ttft_s" and cfg.stat == "p99"
    assert cfg.op == "<" and cfg.threshold == 0.5
    assert cfg.window_s == 60.0 and cfg.objective == 0.99
    assert parse_slo(cfg.spec()) == cfg     # spec() round-trips
    cfgs = parse_slo_args(["serve.ttft_s p99 < 0.5 over 60s; "
                           "serve.queue_depth max < 16 over 10s",
                           "serve.errors_total rate < 1 over 300s"])
    assert len(cfgs) == 3
    for bad in ("nonsense", "serve.ttft_s p42 < 0.5 over 60s",
                "serve.ttft_s p99 ~ 0.5 over 60s",
                "serve.ttft_s p99 < x over 60s"):
        with pytest.raises(ValueError):
            parse_slo(bad)


def test_slo_evaluate_against_view():
    state, clock = _fake_clock()
    store = ts.WindowStore(interval_s=10.0, clock=clock)
    for v in (0.01, 0.02, 0.9):
        store.record_histogram("serve.ttft_s", v)
    view = store.view(60.0)
    ok_cfg = parse_slo("serve.ttft_s p50 < 0.5 over 60s")
    bad_cfg = parse_slo("serve.ttft_s p99 < 0.5 over 60s")
    v_ok = evaluate_slo(ok_cfg, view)
    v_bad = evaluate_slo(bad_cfg, view)
    assert v_ok["ok"] is True and v_ok["no_data"] is False
    assert v_bad["ok"] is False and v_bad["value"] >= 0.5
    # A window that never saw the metric: vacuous ok + no_data.
    v_nd = evaluate_slo(ok_cfg, store.view(60.0, skip=10))
    assert v_nd["ok"] is True and v_nd["no_data"] is True


def test_slo_burn_rate_hand_computed_trace():
    """THE burn-rate pin (ISSUE 16 acceptance): objective 0.9, 8 good
    + 2 bad evaluations -> compliance 0.8, bad fraction 0.2, budget
    0.1, burn rate exactly 2.0."""
    tracker = SLOTracker(parse_slo(
        "serve.ttft_s p99 < 0.5 over 60s objective 0.9"))
    for ok in [True] * 8 + [False] * 2:
        tracker.observe(ok)
    assert tracker.total == 10
    assert tracker.compliance == pytest.approx(0.8)
    assert tracker.bad_fraction() == pytest.approx(0.2)
    assert tracker.burn_rate() == pytest.approx(2.0)
    # Horizon is trailing: 100 more good evaluations dilute the burn.
    for _ in range(100):
        tracker.observe(True)
    assert tracker.burn_rate() == pytest.approx(0.0)
    assert tracker.compliance == pytest.approx(108 / 110)


# ------------------------------------------------------------ watchdog
def _watchdog_rig(interval_s=10.0):
    """An enabled registry with an installed fake-clock window store
    and a watchdog wired to it."""
    obs.enable()
    state, clock = _fake_clock()
    ts.install_windows(interval_s=interval_s, clock=clock)
    return state


def test_watchdog_queue_depth_rising_edge():
    state = _watchdog_rig()
    try:
        wd = Watchdog(config=WatchdogConfig(queue_depth_limit=4.0))
        obs.gauge("serve.queue_depth").set(9)    # min 9 >= 4: sustained
        events = wd.check()
        kinds = [e["kind"] for e in events]
        assert kinds == ["watchdog.queue_depth_sustained"]
        assert events[0]["severity"] == "warning"
        # Still firing: NO repeat event (edge-triggered).
        assert wd.check() == []
        # Clears (queue drained in a fresh window), then re-fires.
        state["t"] += 120.0
        obs.gauge("serve.queue_depth").set(0)
        assert wd.check() == []
        state["t"] += 120.0
        obs.gauge("serve.queue_depth").set(9)
        assert [e["kind"] for e in wd.check()] == \
            ["watchdog.queue_depth_sustained"]
    finally:
        ts.uninstall_windows()
        obs.disable()


def test_watchdog_ttft_regression_vs_trailing_baseline():
    state = _watchdog_rig()
    try:
        wd = Watchdog(config=WatchdogConfig(
            window_s=60.0, baseline_window_s=300.0,
            ttft_regression_factor=2.0, min_samples=8))
        # Healthy history: ~10ms TTFTs across old buckets.
        for _ in range(3):
            for _ in range(10):
                obs.histogram("serve.ttft_s").observe(0.01)
            state["t"] += 60.0
        assert wd.check() == []            # current ~= baseline
        # Regression: the CURRENT window's p99 is 10x the baseline's.
        state["t"] += 60.0
        for _ in range(10):
            obs.histogram("serve.ttft_s").observe(0.1)
        events = wd.check()
        assert [e["kind"] for e in events] == ["watchdog.ttft_regression"]
        d = events[0]["detail"]
        assert d["current_p99"] >= 2.0 * d["baseline_p99"]
        assert events[0]["severity"] == "critical"
    finally:
        ts.uninstall_windows()
        obs.disable()


def test_watchdog_replica_flap_and_slo_burn(tmp_path):
    """Flap + burn rules end to end INTO the run-dir event stream:
    typed records land in events.jsonl and pass the frozen schema."""
    run_dir = str(tmp_path / "wd")
    obs.start_run(run_dir, meta={"kind": "wd_test"},
                  window_interval_s=10.0)
    try:
        slo = parse_slo("serve.ttft_s p99 < 0.05 over 60s objective 0.5")
        wd = Watchdog(slos=[slo],
                      config=WatchdogConfig(flap_limit=3.0,
                                            burn_alert=2.0))
        obs.counter("router.replica_restarts_total").inc(3)
        obs.histogram("serve.ttft_s").observe(0.2)   # violates the SLO
        events = wd.check()
        kinds = [e["kind"] for e in events]
        assert "watchdog.replica_flap" in kinds
        assert "slo.eval" in kinds
        # burn: 1 bad / 1 total over budget 0.5 -> 2.0 >= alert
        assert "watchdog.slo_burn" in kinds
        # Self-instrumentation is pinned schema too.
        assert obs.counter("slo.violations_total").value == 1
        assert obs.gauge("slo.burn_rate_max").value == pytest.approx(2.0)
    finally:
        obs.end_run()
    errors = []
    check_events_jsonl(os.path.join(run_dir, "events.jsonl"), errors)
    assert errors == []
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        streamed = [json.loads(ln) for ln in f if ln.strip()]
    assert [r["kind"] for r in streamed] == kinds


def test_watchdog_thread_runs_and_survives_errors():
    obs.enable()
    try:
        ts.install_windows(interval_s=10.0)
        wd = Watchdog(config=WatchdogConfig())
        t = WatchdogThread(wd, interval_s=0.01).start()
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and obs.counter("watchdog.checks_total").value < 3):
            time.sleep(0.005)
        t.stop()
        assert obs.counter("watchdog.checks_total").value >= 3
        # A check that raises must not kill the loop.
        bad = Watchdog(config=WatchdogConfig())
        bad.check = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        t2 = WatchdogThread(bad, interval_s=0.01).start()
        deadline = time.monotonic() + 30
        while (time.monotonic() < deadline
               and obs.counter("watchdog.check_errors_total").value < 2):
            time.sleep(0.005)
        t2.stop()
        assert obs.counter("watchdog.check_errors_total").value >= 2
    finally:
        ts.uninstall_windows()
        obs.disable()


def test_record_event_disabled_is_noop(tmp_path):
    assert obs.record_event("watchdog.replica_flap") is None
    assert obs.REGISTRY.events == []


# ------------------------------------------------------------ CLI: slo
def test_telemetry_cli_slo_report(tmp_path, capsys):
    """nezha-telemetry RUN_DIR --slo: compliance/burn recomputed from
    the captured slo.eval events, watchdog alerts rendered."""
    from nezha_tpu.cli.telemetry import main as telemetry_main

    run_dir = str(tmp_path / "run")
    obs.start_run(run_dir, meta={"kind": "serve"})
    slo = parse_slo("serve.ttft_s p99 < 0.05 over 60s objective 0.9")
    wd = Watchdog(slos=[slo], config=WatchdogConfig())
    for v in (0.01, 0.01, 0.2):
        ts.current_windows()  # windows installed by start_run
        obs.histogram("serve.ttft_s").observe(v)
        wd.check()
    obs.end_run()

    assert telemetry_main([run_dir, "--slo"]) == 0
    out = capsys.readouterr().out
    assert "SLO report" in out
    assert slo.name in out
    assert telemetry_main([run_dir, "--slo", "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    row = payload["slos"][0]
    assert row["slo"] == slo.name
    assert row["evaluations"] == 3
    kinds = [e["kind"] for e in payload["events"]]
    assert kinds.count("slo.eval") == 3
    # The third eval is bad: 1/3 bad over a 0.1 budget -> burn 3.3
    # trips the default burn_alert=2.0 rule too.
    assert "watchdog.slo_burn" in kinds


def test_serve_cli_slo_flag_validation():
    from nezha_tpu.cli.serve import _start_watchdog, build_parser
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny",
         "--slo", "totally bogus"])
    with pytest.raises(SystemExit, match="--slo"):
        _start_watchdog(args)
    # No SLOs, no interval: watchdog stays off.
    args = build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny"])
    assert _start_watchdog(args) is None


# ------------------------------------------------------------ nezha-top
def test_nezha_top_renders_fleet_frame():
    from nezha_tpu.cli.top import render_top
    obs.enable()
    try:
        ts.install_windows(interval_s=10.0)
        obs.counter("serve.admitted_total").inc(50)
        obs.gauge("serve.queue_depth").set(3)
        obs.gauge("router.replicas_live").set(2)
        for i in range(50):
            obs.histogram("serve.ttft_s").observe(0.01 + 0.001 * i)
        text = ts.render_prometheus(obs.stats_snapshot(),
                                    ts.windows_payload())
    finally:
        ts.uninstall_windows()
        obs.disable()
    frame = render_top(ts.parse_prometheus(text), "60s", url="http://x")
    assert "queue depth" in frame and "ttft (s)" in frame
    assert "replicas live" in frame
    # Degrades readably on an empty scrape.
    assert "no recognized samples" in render_top([], "60s")


def test_nezha_top_polls_http_endpoint(tmp_path):
    """nezha-top main() against a real /metrics HTTP server, bounded
    by --iterations."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from nezha_tpu.cli.top import main as top_main

    obs.enable()
    try:
        ts.install_windows(interval_s=10.0)
        obs.counter("serve.admitted_total").inc(5)
        body = ts.render_prometheus(obs.stats_snapshot(),
                                    ts.windows_payload()).encode()
    finally:
        ts.uninstall_windows()
        obs.disable()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        rc = top_main([f"http://127.0.0.1:{srv.server_address[1]}",
                       "--iterations", "2", "--interval", "0.01",
                       "--no-clear"])
        assert rc == 0
        # Unreachable endpoint: 5 consecutive failures -> exit 1.
        rc = top_main(["http://127.0.0.1:1", "--iterations", "6",
                       "--interval", "0.01", "--no-clear"])
        assert rc == 1
    finally:
        srv.shutdown()
        t.join(timeout=10)


# ------------------------------------------- fleet acceptance (thread)
def _worker_args(extra=()):
    from nezha_tpu.cli.serve import build_parser
    return build_parser().parse_args(
        ["--random-init", "--model-preset", "tiny", "--max-batch-size",
         "2", "--max-len", "48", "--max-prefill-len", "8",
         "--queue-capacity", "8", "--platform", "cpu", *extra])


def _cfg(**kw):
    base = dict(replicas=2, probe_interval_s=0.1, probe_misses=3,
                route_retries=2, retry_backoff_base_s=0.01,
                retry_backoff_max_s=0.05, restart_backoff_base_s=0.05,
                restart_backoff_max_s=0.5, drain_timeout_s=20.0, seed=0)
    base.update(kw)
    return RouterConfig(**base)


def _drive(router, n, salt=0):
    for i in range(n):
        code, obj = router.route(
            {"id": f"m-{salt}-{i}",
             "prompt_tokens": [(7 * j + 3 + i) % 128 for j in range(9)],
             "max_new_tokens": 3, "seed": i})
        assert code == 200, obj


def test_fleet_metrics_acceptance_thread_backend(tmp_path):
    """THE e2e drive: a 2-replica thread fleet under load serves a
    fleet-rolled /metrics (router AND replica endpoints) whose windowed
    TTFT quantiles and queue depth agree with the run-dir artifacts,
    with fleet totals deduped (satellite 1: N thread members sharing
    one process registry count ONCE)."""
    run_dir = str(tmp_path / "fleet")
    cfg = _cfg()
    sup = Supervisor(ThreadBackend(_worker_args(), drain_timeout_s=20.0),
                     cfg)
    router = Router(sup, cfg)
    obs.start_run(run_dir, meta={"kind": "serve_fleet"},
                  window_interval_s=10.0)
    register_router_instruments()
    register_serve_instruments()
    N = 6
    try:
        sup.start()
        assert router.wait_live(2, timeout_s=600), sup.describe()
        _drive(router, N)

        # ---- fleet /stats: deduped totals (the PR 12 over-count fix)
        fleet = router.fleet_stats()
        assert fleet["fleet"]["counters"]["serve.admitted_total"] == N
        # ---- fleet windows: merged payload, deduped member sketches
        fw = router.fleet_windows()
        assert fw["members"] >= 2 and fw["deduped"] >= 1
        view = fw["windows"]["300s"]
        assert view["counters"]["serve.admitted_total"]["delta"] == N
        fleet_h = view["histograms"]["serve.ttft_s"]
        assert fleet_h["count"] == N

        # ---- the fleet /metrics text agrees with the merged windows
        text = router.fleet_metrics_text()
        assert check_metrics_exposition(text) == []
        samples = ts.parse_prometheus(text)
        assert ts.metric_value(samples, "nezha_serve_admitted_total") == N
        got_p99 = ts.metric_value(samples, "nezha_serve_ttft_s",
                                  window="300s", quantile="p99")
        assert got_p99 == pytest.approx(fleet_h["p99"])
        assert ts.metric_value(samples, "nezha_serve_queue_depth_last",
                               window="300s") is not None

        # ---- the replica's own /metrics over real HTTP
        port = sup.replicas()[0].port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            replica_text = r.read().decode()
        assert check_metrics_exposition(replica_text) == []
        rs = ts.parse_prometheus(replica_text)
        assert ts.metric_value(rs, "nezha_serve_admitted_total") == N
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/windows", timeout=30) as r:
            wp = json.loads(r.read())
        assert wp["registry_id"] == obs.REGISTRY.registry_id
    finally:
        obs.end_run()
        router.stop()
        sup.shutdown()

    # ---- the windowed quantiles match the run-dir artifacts: the
    # sketch p99 is within the gamma bound of the summary.json exact
    # reservoir p99 (same N observations, two estimators).
    assert check_run_dir(run_dir) == []
    with open(os.path.join(run_dir, "summary.json")) as f:
        summary = json.load(f)
    exact = summary["histograms"]["serve.ttft_s"]
    assert exact["count"] == N
    assert got_p99 == pytest.approx(
        exact["p99"], rel=2 * (ts.DEFAULT_GAMMA - 1))


def test_fleet_watchdog_trips_on_injected_regression(tmp_path):
    """Acceptance: a fault-injected latency regression mid-run trips
    watchdog.ttft_regression, and the typed event lands schema-valid
    in the run dir's events.jsonl."""
    run_dir = str(tmp_path / "reg")
    cfg = _cfg()
    sup = Supervisor(ThreadBackend(_worker_args(), drain_timeout_s=20.0),
                     cfg)
    router = Router(sup, cfg)
    wd = Watchdog(config=WatchdogConfig(
        window_s=2.0, baseline_window_s=30.0,
        ttft_regression_factor=2.0, min_samples=4))
    try:
        sup.start()
        assert router.wait_live(2, timeout_s=600), sup.describe()
        # Warm up BEFORE starting the instrumented run: the first
        # request pays JIT compile (seconds of TTFT) and with few
        # baseline samples the baseline p99 IS that outlier, masking
        # any later regression.
        _drive(router, 2, salt=9)
        # Short window interval so "healthy history" and "regressed
        # now" land in different buckets within test time.
        obs.start_run(run_dir, meta={"kind": "serve_fleet"},
                      window_interval_s=0.5)
        register_router_instruments()
        register_serve_instruments()
        _drive(router, 6, salt=0)          # healthy baseline traffic
        # Age the healthy traffic past window_s so the check-time
        # CURRENT window holds only fault-phase requests and the
        # trailing baseline (skip excludes the newest 2s) holds the
        # healthy ones.
        time.sleep(2.6)
        assert wd.check() == []            # healthy: no alert
        # Inject a deterministic prefill delay: every request's TTFT
        # regresses by ~100ms against a ~ms baseline.
        faults.install(faults.FaultPlan.parse("serve.prefill:delay=0.1x*"))
        _drive(router, 6, salt=1)
        events = wd.check()
        kinds = [e["kind"] for e in events]
        assert "watchdog.ttft_regression" in kinds, (
            kinds, obs.windows(2.0), obs.windows(30.0, skip=4))
    finally:
        faults.clear()
        obs.end_run()
        router.stop()
        sup.shutdown()
    errors = []
    check_events_jsonl(os.path.join(run_dir, "events.jsonl"), errors)
    assert errors == []
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        streamed = [json.loads(ln) for ln in f if ln.strip()]
    assert any(r["kind"] == "watchdog.ttft_regression"
               for r in streamed)
    assert check_run_dir(run_dir) == []


# ------------------------------------------ thread vs process parity
@pytest.mark.slow
def test_fleet_totals_thread_vs_process_agree(tmp_path):
    """Satellite 1, the cross-backend pin: the SAME load through a
    thread-backed fleet (N members, one shared registry — dedupe) and
    a process-backed fleet (N members, N registries — sum) reports the
    SAME fleet totals. Marked slow: real worker subprocesses."""
    from conftest import worker_env

    from nezha_tpu.cli.serve import _worker_argv

    N = 4
    totals = {}
    for backend_kind in ("thread", "process"):
        cfg = _cfg(replicas=2, probe_timeout_s=10.0)
        if backend_kind == "thread":
            args = _worker_args(["--drain-timeout", "20"])
            backend = ThreadBackend(args, drain_timeout_s=20.0)
        else:
            # Process workers only instrument when telemetry is on:
            # --run-dir gives each replica its own run subdirectory
            # (and its own registry — the fleet roll-up must SUM them,
            # where the thread fleet's shared registry must dedupe).
            args = _worker_args(
                ["--drain-timeout", "20",
                 "--run-dir", str(tmp_path / "proc_run")])
            backend = ProcessBackend(
                lambda rid, port: _worker_argv(args, rid, port),
                env=worker_env(),
                log_dir=str(tmp_path / "logs"))
        sup = Supervisor(backend, cfg)
        router = Router(sup, cfg)
        if backend_kind == "thread":
            obs.enable()
            register_router_instruments()
            register_serve_instruments()
        try:
            sup.start()
            assert router.wait_live(2, timeout_s=600), sup.describe()
            _drive(router, N)
            fleet = router.fleet_stats()
            totals[backend_kind] = \
                fleet["fleet"]["counters"]["serve.admitted_total"]
        finally:
            router.stop()
            sup.shutdown()
            obs.disable()
            obs.REGISTRY.reset()
    assert totals["thread"] == totals["process"] == N, totals
