// Concurrency stress test for the native runtime, built with and without
// ThreadSanitizer (`make stress` / `make stress-tsan`). This is the
// counterpart of running the reference's goroutine runtime under Go's
// -race detector (SURVEY.md §5): hammer the coordinator and loaders from
// many threads and let TSAN prove the locking.
//
// Exit code 0 = clean; TSAN reports turn into a non-zero exit via
// halt_on_error (set in the test harness's TSAN_OPTIONS).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
const char* nz_last_error();
void* nz_coord_start(int port, int world, int hb_timeout_ms);
int nz_coord_port(void* s);
void nz_coord_stop(void* s);
void* nz_client_connect(const char* host, int port, int rank_hint,
                        int timeout_ms, int hb_interval_ms);
int nz_client_rank(void* c);
int nz_client_put(void* c, const char* key, const void* val, long vlen);
long nz_client_get(void* c, const char* key, void* out, long cap,
                   long timeout_ms);
long nz_client_incr(void* c, const char* key);
int nz_client_barrier(void* c, long timeout_ms);
long nz_client_failed(void* c, int* out, long cap);
void nz_client_leave(void* c);
void nz_client_close(void* c);

const char* nz_loader_error();
void* nz_tokens_open(const char* path, int dtype_code, int seq, int batch,
                     uint64_t seed, int workers, int depth, int shard_index,
                     int shard_count, long* n_tokens);
int nz_loader_next(void* l, float* f32_out, int32_t* i32_out);
void nz_loader_close(void* l);
}

static std::atomic<int> g_failures{0};  // CHECKs fire from many threads

#define CHECK(cond, msg)                                   \
  do {                                                     \
    if (!(cond)) {                                         \
      std::fprintf(stderr, "FAIL: %s (%s:%d)\n", msg,      \
                   __FILE__, __LINE__);                    \
      ++g_failures;                                        \
    }                                                      \
  } while (0)

static void coordinator_stress() {
  const int kWorld = 8, kRounds = 30;
  void* server = nz_coord_start(0, kWorld, 5000);
  CHECK(server != nullptr, "coord start");
  int port = nz_coord_port(server);

  std::vector<std::thread> ranks;
  for (int r = 0; r < kWorld; ++r) {
    ranks.emplace_back([port, r] {
      void* c = nz_client_connect("127.0.0.1", port, -1, 10000, 50);
      CHECK(c != nullptr, "client connect");
      if (!c) return;
      int rank = nz_client_rank(c);
      char key[64], buf[256];
      for (int i = 0; i < kRounds; ++i) {
        std::snprintf(key, sizeof(key), "k/%d/%d", rank, i);
        std::snprintf(buf, sizeof(buf), "v-%d-%d", rank, i);
        CHECK(nz_client_put(c, key, buf, std::strlen(buf)) == 0, "put");
        // Read a peer's key from the previous round (blocking get).
        if (i > 0) {
          std::snprintf(key, sizeof(key), "k/%d/%d",
                        (rank + 1) % kWorld, i - 1);
          long n = nz_client_get(c, key, buf, sizeof(buf), 10000);
          CHECK(n > 0, "get peer key");
        }
        long v = nz_client_incr(c, "shared-counter");
        CHECK(v >= 0, "incr");
        int failed[8];
        CHECK(nz_client_failed(c, failed, 8) >= 0, "failed query");
        CHECK(nz_client_barrier(c, 20000) == 0, "barrier");
      }
      nz_client_leave(c);
      nz_client_close(c);
    });
  }
  for (auto& t : ranks) t.join();

  // The shared counter must have been incremented exactly world*rounds.
  void* probe = nz_client_connect("127.0.0.1", port, -1, 5000, 0);
  CHECK(probe != nullptr, "probe connect");
  if (probe) {
    long v = nz_client_incr(probe, "shared-counter");
    CHECK(v == kWorld * kRounds, "counter total");
    nz_client_leave(probe);
    nz_client_close(probe);
  }
  nz_coord_stop(server);
}

static void loader_stress(const char* tmpdir) {
  // Token file: 1M uint16 tokens.
  std::string path = std::string(tmpdir) + "/stress_tokens.bin";
  {
    FILE* f = std::fopen(path.c_str(), "wb");
    CHECK(f != nullptr, "open token file");
    std::vector<uint16_t> toks(1 << 20);
    for (size_t i = 0; i < toks.size(); ++i)
      toks[i] = static_cast<uint16_t>(i & 0x7fff);
    std::fwrite(toks.data(), 2, toks.size(), f);
    std::fclose(f);
  }
  long n_tokens = 0;
  void* l = nz_tokens_open(path.c_str(), 2, 128, 32, 7, 4, 8, 0, 1,
                           &n_tokens);
  CHECK(l != nullptr, "tokens open");
  if (!l) return;
  // Two consumer threads racing the 4 producer workers.
  std::vector<std::thread> consumers;
  for (int t = 0; t < 2; ++t) {
    consumers.emplace_back([l] {
      std::vector<int32_t> out(32 * 129);
      for (int i = 0; i < 200; ++i) {
        int got = nz_loader_next(l, nullptr, out.data());
        CHECK(got == 32, "loader next");
        // Every row's window must be consecutive (source is i & 0x7fff) —
        // torn/interleaved rows are the symptom a loader race would show.
        for (int row = 0; row < 32; ++row) {
          const int32_t* w = out.data() + row * 129;
          for (int j = 1; j < 129; ++j) {
            bool ok = w[j] == ((w[j - 1] + 1) & 0x7fff);
            CHECK(ok, "window continuity");
            if (!ok) return;
          }
        }
      }
    });
  }
  for (auto& t : consumers) t.join();
  nz_loader_close(l);
  std::remove(path.c_str());
}

int main(int argc, char** argv) {
  const char* tmpdir = argc > 1 ? argv[1] : "/tmp";
  coordinator_stress();
  loader_stress(tmpdir);
  if (g_failures.load()) {
    std::fprintf(stderr, "%d failures\n", g_failures.load());
    return 1;
  }
  std::printf("stress OK\n");
  return 0;
}
