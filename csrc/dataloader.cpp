// nezha_tpu native data loader.
//
// Host-side input pipeline in C++, the role the reference's goroutine
// worker pool played on the data path (SURVEY.md §1 "Execution runtime",
// §2 "worker pool runtime"): worker threads decode/assemble batches into a
// bounded queue off the Python thread, so the accelerator never waits on
// the GIL.  Two sources:
//
//   * MNIST IDX files (config 1 of BASELINE.json): big-endian IDX parsing,
//     per-epoch shuffling, normalized float32 images + int32 labels.
//   * Packed token files (configs 3/4, GPT-2/BERT-style LM data): a flat
//     binary array of uint16/int32 token ids, sampled as [batch, seq+1]
//     windows for next-token prediction.
//
// Batches are copied into caller-provided buffers (numpy arrays on the
// Python side) — the ctypes call releases the GIL, workers keep producing.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

thread_local std::string g_loader_error;
void set_loader_error(const std::string& e) { g_loader_error = e; }

// ------------------------------------------------------------ batch queue
struct Batch {
  std::vector<float> f32;     // images
  std::vector<int32_t> i32;   // labels / tokens
  int count = 0;              // examples in this batch
};

class BatchQueue {
 public:
  explicit BatchQueue(size_t depth) : depth_(depth) {}

  bool Push(Batch&& b) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_push_.wait(lk, [this] { return stopped_ || q_.size() < depth_; });
    if (stopped_) return false;
    q_.push_back(std::move(b));
    cv_pop_.notify_one();
    return true;
  }

  bool Pop(Batch* out) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [this] { return stopped_ || !q_.empty(); });
    if (q_.empty()) return false;  // stopped and drained
    *out = std::move(q_.front());
    q_.pop_front();
    cv_push_.notify_one();
    return true;
  }

  void Stop() {
    std::lock_guard<std::mutex> lk(mu_);
    stopped_ = true;
    cv_push_.notify_all();
    cv_pop_.notify_all();
  }

 private:
  const size_t depth_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  std::deque<Batch> q_;
  bool stopped_ = false;
};

// --------------------------------------------------------------- base type
class Loader {
 public:
  Loader(int batch, size_t depth) : batch_(batch), queue_(depth) {}
  virtual ~Loader() { StopWorkers(); }

  // Returns examples copied (== batch size), 0 on shutdown, -1 on error.
  int Next(float* f32_out, int32_t* i32_out) {
    Batch b;
    if (!queue_.Pop(&b)) return error_.empty() ? 0 : -1;
    if (f32_out && !b.f32.empty())
      std::memcpy(f32_out, b.f32.data(), b.f32.size() * sizeof(float));
    if (i32_out && !b.i32.empty())
      std::memcpy(i32_out, b.i32.data(), b.i32.size() * sizeof(int32_t));
    return b.count;
  }

  int batch() const { return batch_; }

 protected:
  void StartWorkers(int n) {
    // num_workers_ and active_workers_ must be set before any thread runs:
    // a thread can enter WorkerLoop before emplace_back even returns, so
    // workers_.size() is not safe to read from the loop.
    num_workers_ = n;
    active_workers_ = n;
    for (int i = 0; i < n; ++i)
      workers_.emplace_back([this, i] { WorkerLoop(i); });
  }

  // Finite sources call this when a worker exhausts its share; the queue is
  // only stopped once every worker is done, so no batch is dropped.
  void WorkerDone() {
    if (--active_workers_ == 0) queue_.Stop();
  }

  void StopWorkers() {
    stopping_ = true;
    queue_.Stop();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    workers_.clear();
  }

  virtual void WorkerLoop(int worker_id) = 0;

  const int batch_;
  BatchQueue queue_;
  std::atomic<bool> stopping_{false};
  std::string error_;
  int num_workers_ = 1;
  std::atomic<int> active_workers_{0};
  std::vector<std::thread> workers_;
};

// --------------------------------------------------------------- MNIST IDX
uint32_t be32(const unsigned char* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

bool read_file(const std::string& path, std::vector<unsigned char>* out) {
  FILE* f = std::fopen(path.c_str(), "rb");
  if (!f) return false;
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  out->resize(static_cast<size_t>(n));
  size_t got = n ? std::fread(out->data(), 1, static_cast<size_t>(n), f) : 0;
  std::fclose(f);
  return got == static_cast<size_t>(n);
}

class MnistLoader : public Loader {
 public:
  MnistLoader(const char* images_path, const char* labels_path, int batch,
              uint64_t seed, int workers, size_t depth, int epochs)
      : Loader(batch, depth), seed_(seed), epochs_(epochs) {
    std::vector<unsigned char> img_raw, lbl_raw;
    if (!read_file(images_path, &img_raw) ||
        !read_file(labels_path, &lbl_raw)) {
      error_ = "cannot read MNIST files";
      return;
    }
    if (img_raw.size() < 16 || be32(img_raw.data()) != 2051 ||
        lbl_raw.size() < 8 || be32(lbl_raw.data()) != 2049) {
      error_ = "bad IDX magic";
      return;
    }
    n_ = be32(img_raw.data() + 4);
    rows_ = be32(img_raw.data() + 8);
    cols_ = be32(img_raw.data() + 12);
    if (be32(lbl_raw.data() + 4) != n_ ||
        img_raw.size() < 16 + size_t(n_) * rows_ * cols_ ||
        lbl_raw.size() < 8 + size_t(n_)) {  // truncated label body
      error_ = "IDX size mismatch";
      return;
    }
    if (batch_ <= 0 || static_cast<uint32_t>(batch_) > n_) {
      // With batch > n, nbatch == 0 and infinite epochs would spin forever
      // with no stopping_ check reachable (workers hang in join on close).
      error_ = "batch size must be in [1, num examples]";
      return;
    }
    pixels_.assign(img_raw.begin() + 16, img_raw.end());
    labels_.assign(lbl_raw.begin() + 8, lbl_raw.end());
    StartWorkers(std::max(workers, 1));
  }

  // Join workers before this class's members (pixels_, labels_) are
  // destroyed — the base destructor would join too late.
  ~MnistLoader() override { StopWorkers(); }

  bool ok() const { return error_.empty(); }
  const std::string& error() const { return error_; }
  uint32_t n() const { return n_; }
  uint32_t dim() const { return rows_ * cols_; }

 protected:
  void WorkerLoop(int worker_id) override {
    const size_t dim = rows_ * cols_;
    // A worker whose stride never reaches a batch index can never produce;
    // exit now instead of spinning shuffles forever under infinite epochs.
    if (static_cast<size_t>(worker_id) >= size_t(n_) / batch_) {
      WorkerDone();
      return;
    }
    for (int epoch = 0; epochs_ <= 0 || epoch < epochs_; ++epoch) {
      if (stopping_) return;
      // All workers derive the same per-epoch permutation and take strided
      // slices of it, so every example appears exactly once per epoch.
      std::vector<uint32_t> perm(n_);
      for (uint32_t i = 0; i < n_; ++i) perm[i] = i;
      std::mt19937_64 rng(seed_ + static_cast<uint64_t>(epoch));
      std::shuffle(perm.begin(), perm.end(), rng);
      const size_t nbatch = n_ / batch_;  // drop remainder
      for (size_t b = static_cast<size_t>(worker_id); b < nbatch;
           b += static_cast<size_t>(num_workers_)) {
        if (stopping_) return;
        Batch out;
        out.count = batch_;
        out.f32.resize(static_cast<size_t>(batch_) * dim);
        out.i32.resize(batch_);
        for (int j = 0; j < batch_; ++j) {
          uint32_t idx = perm[b * batch_ + j];
          const unsigned char* src = pixels_.data() + size_t(idx) * dim;
          float* dst = out.f32.data() + size_t(j) * dim;
          for (size_t k = 0; k < dim; ++k)
            dst[k] = static_cast<float>(src[k]) * (1.0f / 255.0f);
          out.i32[j] = labels_[idx];
        }
        if (!queue_.Push(std::move(out))) return;
      }
    }
    WorkerDone();  // finite epochs: last worker out signals end-of-data
  }

 private:
  uint32_t n_ = 0, rows_ = 0, cols_ = 0;
  std::vector<unsigned char> pixels_;
  std::vector<unsigned char> labels_;
  const uint64_t seed_;
  const int epochs_;
};

// ------------------------------------------------------------ token files
class TokenLoader : public Loader {
 public:
  // dtype_code: 2 = uint16, 4 = int32.
  // shard_index decorrelates the random-window streams across hosts (the
  // stream is infinite/sampled, so sharding is a seed split, not a
  // partition).
  TokenLoader(const char* path, int dtype_code, int seq, int batch,
              uint64_t seed, int workers, size_t depth, int shard_index,
              int shard_count)
      : Loader(batch, depth), seq_(seq),
        seed_(seed + 0xd1342543de82ef95ULL * static_cast<uint64_t>(shard_index)) {
    if (shard_count < 1 || shard_index < 0 || shard_index >= shard_count) {
      error_ = "need 0 <= shard_index < shard_count";
      return;
    }
    std::vector<unsigned char> raw;
    if (!read_file(path, &raw)) {
      error_ = "cannot read token file";
      return;
    }
    if (dtype_code == 2) {
      size_t n = raw.size() / 2;
      tokens_.resize(n);
      const uint16_t* p = reinterpret_cast<const uint16_t*>(raw.data());
      for (size_t i = 0; i < n; ++i) tokens_[i] = p[i];
    } else if (dtype_code == 4) {
      size_t n = raw.size() / 4;
      tokens_.resize(n);
      std::memcpy(tokens_.data(), raw.data(), n * 4);
    } else {
      error_ = "dtype_code must be 2 (uint16) or 4 (int32)";
      return;
    }
    if (tokens_.size() < static_cast<size_t>(seq) + 1) {
      error_ = "token file shorter than seq+1";
      return;
    }
    StartWorkers(std::max(workers, 1));
  }

  ~TokenLoader() override { StopWorkers(); }  // see MnistLoader note

  bool ok() const { return error_.empty(); }
  const std::string& error() const { return error_; }
  size_t n_tokens() const { return tokens_.size(); }

 protected:
  void WorkerLoop(int worker_id) override {
    // Random [seq+1] windows, GPT-style; stream is infinite.
    std::mt19937_64 rng(seed_ * 6364136223846793005ULL +
                        static_cast<uint64_t>(worker_id) + 1);
    std::uniform_int_distribution<size_t> dist(
        0, tokens_.size() - static_cast<size_t>(seq_) - 1);
    const size_t w = static_cast<size_t>(seq_) + 1;
    while (!stopping_) {
      Batch out;
      out.count = batch_;
      out.i32.resize(static_cast<size_t>(batch_) * w);
      for (int j = 0; j < batch_; ++j) {
        size_t start = dist(rng);
        std::memcpy(out.i32.data() + size_t(j) * w, tokens_.data() + start,
                    w * sizeof(int32_t));
      }
      if (!queue_.Push(std::move(out))) return;
    }
  }

 private:
  const int seq_;
  const uint64_t seed_;
  std::vector<int32_t> tokens_;
};

// ----------------------------------------------------------- image records
// ImageNet-style path (SURVEY.md §1 "MNIST + ImageNet + text loaders"):
// pre-decoded raw images in a flat record file — "NZR1" magic, then
// int32 n, h, w, c (little-endian), then n records of (int32 label +
// h*w*c uint8 HWC pixels). JPEG decode happens once at dataset-prep time
// (no image codec in this runtime); the loader does the per-epoch work:
// shuffle, random crop, horizontal flip, normalize — on worker threads.
class ImageRecordLoader : public Loader {
 public:
  // shard_index/shard_count: multi-host data sharding. Every shard derives
  // the SAME per-epoch permutation (seed-keyed) and takes batches
  // b ≡ shard_index (mod shard_count), so across the world each record is
  // consumed exactly once per epoch with zero coordination traffic.
  ImageRecordLoader(const char* path, int batch, int crop_h, int crop_w,
                    uint64_t seed, int workers, size_t depth, int epochs,
                    bool train_augment, int shard_index, int shard_count)
      : Loader(batch, depth), crop_h_(crop_h), crop_w_(crop_w),
        seed_(seed), epochs_(epochs), augment_(train_augment),
        shard_index_(shard_index), shard_count_(shard_count) {
    if (shard_count_ < 1 || shard_index_ < 0 || shard_index_ >= shard_count_) {
      error_ = "need 0 <= shard_index < shard_count";
      return;
    }
    if (!read_file(path, &raw_)) {
      error_ = "cannot read record file";
      return;
    }
    if (raw_.size() < 20 || std::memcmp(raw_.data(), "NZR1", 4) != 0) {
      error_ = "bad NZR1 magic";
      return;
    }
    int32_t dims[4];
    std::memcpy(dims, raw_.data() + 4, 16);
    n_ = dims[0]; h_ = dims[1]; w_ = dims[2]; c_ = dims[3];
    // Bound each dim before multiplying: a crafted header could overflow
    // the pixel product and slip past the size check into OOB reads.
    if (n_ <= 0 || h_ <= 0 || w_ <= 0 || c_ <= 0 ||
        h_ > (1 << 16) || w_ > (1 << 16) || c_ > 64) {
      error_ = "NZR1 bad dimensions";
      return;
    }
    record_ = 4 + size_t(h_) * w_ * c_;  // <= 2^38, no overflow
    // Divide instead of multiplying: n_ * record_ could wrap 64 bits.
    if (raw_.size() < 20 || size_t(n_) > (raw_.size() - 20) / record_) {
      error_ = "NZR1 size mismatch";
      return;
    }
    if (batch > n_) {
      error_ = "batch size exceeds number of records";
      return;
    }
    if (size_t(n_) / batch < static_cast<size_t>(shard_count_)) {
      // A shard with zero batches would silently starve its host.
      error_ = "shard_count exceeds batches per epoch";
      return;
    }
    if (crop_h_ <= 0) crop_h_ = h_;
    if (crop_w_ <= 0) crop_w_ = w_;
    if (crop_h_ > h_ || crop_w_ > w_) {
      error_ = "crop larger than stored image";
      return;
    }
    StartWorkers(std::max(workers, 1));
  }

  ~ImageRecordLoader() override { StopWorkers(); }  // see MnistLoader note

  bool ok() const { return error_.empty(); }
  const std::string& error() const { return error_; }
  int n() const { return n_; }
  int h() const { return h_; }
  int w() const { return w_; }
  int c() const { return c_; }
  int crop_h() const { return crop_h_; }
  int crop_w() const { return crop_w_; }

 protected:
  void WorkerLoop(int worker_id) override {
    const size_t out_px = size_t(crop_h_) * crop_w_ * c_;
    const size_t shard0 = static_cast<size_t>(shard_index_);
    const size_t sstride = static_cast<size_t>(shard_count_);
    // Every shard serves exactly floor(nbatch / shard_count) batches per
    // epoch (the ragged tail is dropped): lockstep multi-host consumers
    // would otherwise deadlock when a short shard exhausts first.
    const size_t nbatch_shard = (size_t(n_) / batch_) / sstride;
    // s enumerates this shard's batch series; this worker takes every
    // num_workers-th element of it. Global batch index b = shard0 + s*stride.
    if (static_cast<size_t>(worker_id) >= nbatch_shard) {
      WorkerDone();  // can never produce a batch; see MnistLoader note
      return;
    }
    for (int epoch = 0; epochs_ <= 0 || epoch < epochs_; ++epoch) {
      if (stopping_) return;
      std::vector<uint32_t> perm(n_);
      for (int i = 0; i < n_; ++i) perm[i] = static_cast<uint32_t>(i);
      std::mt19937_64 perm_rng(seed_ + static_cast<uint64_t>(epoch));
      std::shuffle(perm.begin(), perm.end(), perm_rng);
      for (size_t s = static_cast<size_t>(worker_id); s < nbatch_shard;
           s += static_cast<size_t>(num_workers_)) {
        const size_t b = shard0 + s * sstride;
        if (stopping_) return;
        // Augmentation rng keyed by (seed, epoch, batch index): identical
        // batches regardless of which worker drew them.
        std::mt19937_64 rng((seed_ + 0x9e3779b97f4a7c15ULL * (epoch + 1)) ^
                            (b + 1));
        Batch out;
        out.count = batch_;
        out.f32.resize(static_cast<size_t>(batch_) * out_px);
        out.i32.resize(batch_);
        for (int j = 0; j < batch_; ++j) {
          const unsigned char* rec =
              raw_.data() + 20 + size_t(perm[b * batch_ + j]) * record_;
          int32_t label;
          std::memcpy(&label, rec, 4);
          out.i32[j] = label;
          const unsigned char* px = rec + 4;
          int dy = 0, dx = 0;
          bool flip = false;
          if (augment_) {
            if (h_ > crop_h_)
              dy = static_cast<int>(rng() % (uint64_t)(h_ - crop_h_ + 1));
            if (w_ > crop_w_)
              dx = static_cast<int>(rng() % (uint64_t)(w_ - crop_w_ + 1));
            flip = (rng() & 1) != 0;
          } else {  // eval: deterministic center crop
            dy = (h_ - crop_h_) / 2;
            dx = (w_ - crop_w_) / 2;
          }
          float* dst = out.f32.data() + size_t(j) * out_px;
          for (int y = 0; y < crop_h_; ++y) {
            const unsigned char* row =
                px + (size_t(y + dy) * w_ + dx) * c_;
            float* drow = dst + size_t(y) * crop_w_ * c_;
            if (!flip) {
              for (int i = 0; i < crop_w_ * c_; ++i)
                drow[i] = static_cast<float>(row[i]) * (1.0f / 255.0f);
            } else {
              for (int x = 0; x < crop_w_; ++x)
                for (int ch = 0; ch < c_; ++ch)
                  drow[size_t(x) * c_ + ch] =
                      static_cast<float>(
                          row[size_t(crop_w_ - 1 - x) * c_ + ch]) *
                      (1.0f / 255.0f);
            }
          }
        }
        if (!queue_.Push(std::move(out))) return;
      }
    }
    WorkerDone();
  }

 private:
  int n_ = 0, h_ = 0, w_ = 0, c_ = 0;
  int crop_h_, crop_w_;
  size_t record_ = 0;
  std::vector<unsigned char> raw_;
  const uint64_t seed_;
  const int epochs_;
  const bool augment_;
  const int shard_index_, shard_count_;
};

}  // namespace

// ------------------------------------------------------------------- C ABI
extern "C" {

const char* nz_loader_error() { return g_loader_error.c_str(); }

void* nz_mnist_open(const char* images_path, const char* labels_path,
                    int batch, uint64_t seed, int workers, int depth,
                    int epochs, int* n_out, int* dim_out) {
  auto* l = new MnistLoader(images_path, labels_path, batch, seed, workers,
                            static_cast<size_t>(depth), epochs);
  if (!l->ok()) {
    set_loader_error(l->error());
    delete l;
    return nullptr;
  }
  if (n_out) *n_out = static_cast<int>(l->n());
  if (dim_out) *dim_out = static_cast<int>(l->dim());
  return l;
}

void* nz_tokens_open(const char* path, int dtype_code, int seq, int batch,
                     uint64_t seed, int workers, int depth, int shard_index,
                     int shard_count, long* n_tokens) {
  auto* l = new TokenLoader(path, dtype_code, seq, batch, seed, workers,
                            static_cast<size_t>(depth), shard_index,
                            shard_count);
  if (!l->ok()) {
    set_loader_error(l->error());
    delete l;
    return nullptr;
  }
  if (n_tokens) *n_tokens = static_cast<long>(l->n_tokens());
  return l;
}

void* nz_records_open(const char* path, int batch, int crop_h, int crop_w,
                      uint64_t seed, int workers, int depth, int epochs,
                      int train_augment, int shard_index, int shard_count,
                      int* n_out, int* h_out, int* w_out, int* c_out) {
  auto* l = new ImageRecordLoader(path, batch, crop_h, crop_w, seed, workers,
                                  static_cast<size_t>(depth), epochs,
                                  train_augment != 0, shard_index,
                                  shard_count);
  if (!l->ok()) {
    set_loader_error(l->error());
    delete l;
    return nullptr;
  }
  if (n_out) *n_out = l->n();
  if (h_out) *h_out = l->crop_h();
  if (w_out) *w_out = l->crop_w();
  if (c_out) *c_out = l->c();
  return l;
}

// Blocks until a batch is ready; returns examples copied, 0 at end-of-data.
int nz_loader_next(void* l, float* f32_out, int32_t* i32_out) {
  return static_cast<Loader*>(l)->Next(f32_out, i32_out);
}

void nz_loader_close(void* l) { delete static_cast<Loader*>(l); }

}  // extern "C"
