// nezha_tpu native coordinator.
//
// TPU-native counterpart of the reference's gRPC coordinator (SURVEY.md §1
// "Distributed runtime", §2 "gRPC coordinator"): rank rendezvous, a small
// key/value store for topology exchange (the role NCCL-unique-id broadcast
// played in the reference; here it carries PJRT/jax.distributed addresses
// or any rendezvous blob), a world barrier, and heartbeat-based failure
// detection.  Plain TCP with a length-prefixed binary protocol — no RPC
// framework dependency — exposed through a C ABI for Python ctypes.
//
// Threading model: the server runs an accept loop plus one thread per
// connection (world sizes are the number of *hosts*, small); shared state
// is one mutex + condition_variable.  Blocking semantics (GET waits for a
// key, BARRIER waits for the world) are implemented as cv waits on the
// connection's thread, so the protocol stays strictly request/reply.

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// ---------------------------------------------------------------- protocol
enum MsgType : uint32_t {
  MSG_HELLO = 1,      // val: int32 rank_hint (-1 = assign any)
  MSG_PUT = 2,        // key + val
  MSG_GET = 3,        // key; val: int64 timeout_ms
  MSG_BARRIER = 4,    // val: int64 timeout_ms
  MSG_HEARTBEAT = 5,  // no payload
  MSG_FAILED = 6,     // no payload -> VAL int32[] failed ranks
  MSG_LEAVE = 7,      // graceful departure
  MSG_INCR = 8,       // key -> VAL int64 previous counter value
  MSG_OK = 100,
  MSG_VAL = 101,
  MSG_ERR = 102,
  MSG_ASSIGN = 103,  // val: int32 rank, int32 world
};

struct Header {
  uint32_t type;
  uint32_t klen;
  uint32_t vlen;
};

thread_local std::string g_error;

void set_error(const std::string& e) { g_error = e; }

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool send_msg(int fd, uint32_t type, const std::string& key,
              const std::string& val) {
  Header h{type, static_cast<uint32_t>(key.size()),
           static_cast<uint32_t>(val.size())};
  if (!write_full(fd, &h, sizeof(h))) return false;
  if (!key.empty() && !write_full(fd, key.data(), key.size())) return false;
  if (!val.empty() && !write_full(fd, val.data(), val.size())) return false;
  return true;
}

// 64 MiB cap on any single payload — rendezvous blobs are tiny; this is a
// guard against a corrupt header, not a real limit.
constexpr uint32_t kMaxPayload = 64u << 20;

bool recv_msg(int fd, uint32_t* type, std::string* key, std::string* val) {
  Header h;
  if (!read_full(fd, &h, sizeof(h))) return false;
  if (h.klen > kMaxPayload || h.vlen > kMaxPayload) return false;
  key->resize(h.klen);
  val->resize(h.vlen);
  if (h.klen && !read_full(fd, &(*key)[0], h.klen)) return false;
  if (h.vlen && !read_full(fd, &(*val)[0], h.vlen)) return false;
  *type = h.type;
  return true;
}

std::string pack_i32(int32_t a) {
  std::string s(4, '\0');
  std::memcpy(&s[0], &a, 4);
  return s;
}

std::string pack_i32x2(int32_t a, int32_t b) {
  std::string s(8, '\0');
  std::memcpy(&s[0], &a, 4);
  std::memcpy(&s[4], &b, 4);
  return s;
}

int64_t unpack_i64(const std::string& s, int64_t dflt) {
  if (s.size() < 8) return dflt;
  int64_t v;
  std::memcpy(&v, s.data(), 8);
  return v;
}

// ------------------------------------------------------------------ server
class CoordServer {
 public:
  CoordServer(int port, int world, int hb_timeout_ms)
      : world_(world), hb_timeout_ms_(hb_timeout_ms) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
        0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    ::listen(listen_fd_, 128);
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~CoordServer() { Stop(); }

  bool ok() const { return listen_fd_ >= 0; }
  int port() const { return port_; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : conn_threads_)
      if (t.joinable()) t.join();
  }

 private:
  void AcceptLoop() {
    while (!stopping_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu_);
      ReapFinishedLocked();  // bound thread growth across elastic restarts
      conn_fds_.insert(fd);
      conn_threads_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  // Join connection threads that have announced exit (Serve pushes its id as
  // its last action). A long-lived coordinator serving many reconnects would
  // otherwise accumulate exited-but-joinable threads without bound.
  void ReapFinishedLocked() {
    for (auto id : done_ids_) {
      for (auto it = conn_threads_.begin(); it != conn_threads_.end(); ++it) {
        if (it->get_id() == id) {
          it->join();
          conn_threads_.erase(it);
          break;
        }
      }
    }
    done_ids_.clear();
  }

  void Serve(int fd) {
    int rank = -1;       // set by HELLO
    uint64_t gen = 0;    // this connection's claim on the rank
    bool disconnected = false;
    uint32_t type = 0;
    std::string key, val;
    while (!stopping_ && !disconnected && recv_msg(fd, &type, &key, &val)) {
      switch (type) {
        case MSG_HELLO: {
          std::unique_lock<std::mutex> lk(mu_);
          int32_t hint = -1;
          if (val.size() >= 4) std::memcpy(&hint, val.data(), 4);
          if (hint >= 0 && hint < world_ && !assigned_.count(hint)) {
            rank = hint;
          } else {
            for (int r = 0; r < world_; ++r)
              if (!assigned_.count(r)) {
                rank = r;
                break;
              }
          }
          if (rank < 0) {
            lk.unlock();
            send_msg(fd, MSG_ERR, "", "world full");
            continue;
          }
          assigned_.insert(rank);
          last_seen_[rank] = Clock::now();
          // A rank slot freed by crash or LEAVE is reclaimable (restart
          // workflow: supervisor relaunches the rank, it rejoins).
          failed_.erase(rank);
          left_.erase(rank);
          gen = ++conn_gen_[rank];
          lk.unlock();
          send_msg(fd, MSG_ASSIGN, "", pack_i32x2(rank, world_));
          break;
        }
        case MSG_PUT: {
          {
            std::lock_guard<std::mutex> lk(mu_);
            kv_[key] = val;
            Touch(rank);
          }
          cv_.notify_all();
          send_msg(fd, MSG_OK, "", "");
          break;
        }
        case MSG_GET: {
          int64_t timeout_ms = unpack_i64(val, -1);
          std::unique_lock<std::mutex> lk(mu_);
          Touch(rank);
          auto pred = [&] { return stopping_ || kv_.count(key) > 0; };
          int w = WaitBlocking(lk, fd, rank, timeout_ms, pred);
          if (stopping_) break;  // fall out to cleanup: close fd, drop conn
          if (w < 0) { disconnected = true; break; }
          if (w == 0) {
            lk.unlock();
            send_msg(fd, MSG_ERR, "", "get timeout: " + key);
            break;
          }
          std::string out = kv_[key];
          lk.unlock();
          send_msg(fd, MSG_VAL, "", out);
          break;
        }
        case MSG_BARRIER: {
          int64_t timeout_ms = unpack_i64(val, -1);
          std::unique_lock<std::mutex> lk(mu_);
          Touch(rank);
          uint64_t my_epoch = barrier_epoch_;
          if (++barrier_count_ == world_) {
            barrier_count_ = 0;
            ++barrier_epoch_;
            cv_.notify_all();
          }
          auto pred = [&] { return stopping_ || barrier_epoch_ > my_epoch; };
          int w = WaitBlocking(lk, fd, rank, timeout_ms, pred);
          if (stopping_) break;  // fall out to cleanup: close fd, drop conn
          if (w <= 0) {
            // Withdraw from the still-pending epoch so a later retry (or
            // this rank's failure) doesn't double-count it.
            if (barrier_epoch_ == my_epoch && barrier_count_ > 0)
              --barrier_count_;
            if (w < 0) { disconnected = true; break; }
            lk.unlock();
            send_msg(fd, MSG_ERR, "", "barrier timeout");
            break;
          }
          lk.unlock();
          send_msg(fd, MSG_OK, "", "");
          break;
        }
        case MSG_INCR: {
          // Server-side fetch-and-increment. Collective round counters
          // live here (not in the client) so a crashed-and-rejoined rank
          // resumes at the world's current round instead of round 0.
          int64_t old;
          {
            std::lock_guard<std::mutex> lk(mu_);
            old = counters_[key]++;
            Touch(rank);
          }
          std::string out(8, '\0');
          std::memcpy(&out[0], &old, 8);
          send_msg(fd, MSG_VAL, "", out);
          break;
        }
        case MSG_HEARTBEAT: {
          {
            std::lock_guard<std::mutex> lk(mu_);
            Touch(rank);
          }
          send_msg(fd, MSG_OK, "", "");
          break;
        }
        case MSG_FAILED: {
          std::string out;
          {
            std::lock_guard<std::mutex> lk(mu_);
            Touch(rank);
            auto now = Clock::now();
            std::set<int> failed = failed_;
            for (auto& kvp : last_seen_) {
              auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                            now - kvp.second)
                            .count();
              if (ms > hb_timeout_ms_) failed.insert(kvp.first);
            }
            for (int r : failed) out += pack_i32(r);
          }
          send_msg(fd, MSG_VAL, "", out);
          break;
        }
        case MSG_LEAVE: {
          {
            std::lock_guard<std::mutex> lk(mu_);
            if (rank >= 0 && conn_gen_[rank] == gen) {
              left_.insert(rank);
              assigned_.erase(rank);  // slot reusable by a replacement
              last_seen_.erase(rank);
            }
          }
          send_msg(fd, MSG_OK, "", "");
          break;
        }
        default:
          send_msg(fd, MSG_ERR, "", "bad message type");
      }
    }
    // Connection dropped: a rank that never sent LEAVE is failed. The gen
    // check keeps a stale connection's teardown from clobbering a
    // replacement process that already re-claimed the rank.
    {
      std::lock_guard<std::mutex> lk(mu_);
      conn_fds_.erase(fd);
      if (rank >= 0 && conn_gen_[rank] == gen && !left_.count(rank)) {
        failed_.insert(rank);
        assigned_.erase(rank);  // slot reusable by a replacement
        last_seen_.erase(rank);
      }
      done_ids_.push_back(std::this_thread::get_id());
    }
    cv_.notify_all();
    ::close(fd);
  }

  void Touch(int rank) {
    if (rank >= 0) last_seen_[rank] = Clock::now();
  }

  // Wait for `pred` under `lk` in short slices. Each slice refreshes the
  // rank's liveness — a connection whose thread is servicing a blocking
  // GET/BARRIER is proof of life even though the client's heartbeat is
  // queued behind the in-flight request — and probes the socket so a peer
  // that dies mid-wait is detected instead of waited on forever.
  // Returns 1 released, 0 timeout, -1 peer disconnected.
  template <typename Pred>
  int WaitBlocking(std::unique_lock<std::mutex>& lk, int fd, int rank,
                   int64_t timeout_ms, Pred pred) {
    const bool bounded = timeout_ms >= 0;
    const auto deadline =
        Clock::now() + std::chrono::milliseconds(bounded ? timeout_ms : 0);
    while (!pred()) {
      auto slice = std::chrono::milliseconds(200);
      if (bounded) {
        auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
            deadline - Clock::now());
        if (left.count() <= 0) return 0;
        slice = std::min(slice, left);
      }
      cv_.wait_for(lk, slice);
      Touch(rank);
      char probe;
      ssize_t r = ::recv(fd, &probe, 1, MSG_PEEK | MSG_DONTWAIT);
      if (r == 0) return -1;  // orderly shutdown by peer
    }
    return 1;
  }

  int listen_fd_ = -1;
  int port_ = 0;
  const int world_;
  const int hb_timeout_ms_;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::vector<std::thread> conn_threads_;
  std::vector<std::thread::id> done_ids_;

  std::mutex mu_;
  std::condition_variable cv_;
  std::set<int> conn_fds_;
  std::set<int> assigned_;
  std::set<int> left_;
  std::set<int> failed_;
  std::map<int, uint64_t> conn_gen_;
  std::map<int, Clock::time_point> last_seen_;
  std::map<std::string, std::string> kv_;
  std::map<std::string, int64_t> counters_;
  int barrier_count_ = 0;
  uint64_t barrier_epoch_ = 0;
};

// ------------------------------------------------------------------ client
class CoordClient {
 public:
  CoordClient(const char* host, int port, int rank_hint, int timeout_ms,
              int hb_interval_ms) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);
    // Retry connect until the deadline: clients may start before the
    // coordinator (the reference's rendezvous tolerated launch skew).
    while (fd_ < 0) {
      if (::getaddrinfo(host, port_s.c_str(), &hints, &res) == 0) {
        int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
        if (::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          fd_ = fd;
        } else {
          ::close(fd);
        }
        ::freeaddrinfo(res);
        res = nullptr;
      }
      if (fd_ < 0) {
        if (Clock::now() >= deadline) {
          set_error("connect timeout");
          return;
        }
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
      }
    }
    uint32_t type = 0;
    std::string key, val;
    if (!Request(MSG_HELLO, "", pack_i32(rank_hint), &type, &val) ||
        type != MSG_ASSIGN || val.size() < 8) {
      set_error(type == MSG_ERR ? val : "rendezvous failed");
      ::close(fd_);
      fd_ = -1;
      return;
    }
    std::memcpy(&rank_, val.data(), 4);
    std::memcpy(&world_, val.data() + 4, 4);
    if (hb_interval_ms > 0) {
      hb_thread_ = std::thread([this, hb_interval_ms] {
        while (!closing_) {
          std::unique_lock<std::mutex> lk(hb_mu_);
          hb_cv_.wait_for(lk, std::chrono::milliseconds(hb_interval_ms),
                          [this] { return closing_.load(); });
          if (closing_) return;
          uint32_t t = 0;
          std::string v;
          if (!Request(MSG_HEARTBEAT, "", "", &t, &v)) return;
        }
      });
    }
  }

  ~CoordClient() { Close(false); }

  bool ok() const { return fd_ >= 0; }
  int rank() const { return rank_; }
  int world() const { return world_; }

  bool Put(const std::string& key, const std::string& val) {
    uint32_t type = 0;
    std::string out;
    if (!Request(MSG_PUT, key, val, &type, &out) || type != MSG_OK) {
      set_error(type == MSG_ERR ? out : "put failed");
      return false;
    }
    return true;
  }

  bool Get(const std::string& key, int64_t timeout_ms, std::string* out) {
    std::string t(8, '\0');
    std::memcpy(&t[0], &timeout_ms, 8);
    uint32_t type = 0;
    if (!Request(MSG_GET, key, t, &type, out) || type != MSG_VAL) {
      set_error(type == MSG_ERR ? *out : "get failed");
      return false;
    }
    return true;
  }

  int64_t Incr(const std::string& key) {
    uint32_t type = 0;
    std::string out;
    if (!Request(MSG_INCR, key, "", &type, &out) || type != MSG_VAL ||
        out.size() < 8) {
      set_error(type == MSG_ERR ? out : "incr failed");
      return -1;
    }
    int64_t v;
    std::memcpy(&v, out.data(), 8);
    return v;
  }

  bool Barrier(int64_t timeout_ms) {
    std::string t(8, '\0');
    std::memcpy(&t[0], &timeout_ms, 8);
    uint32_t type = 0;
    std::string out;
    if (!Request(MSG_BARRIER, "", t, &type, &out) || type != MSG_OK) {
      set_error(type == MSG_ERR ? out : "barrier failed");
      return false;
    }
    return true;
  }

  bool Failed(std::vector<int32_t>* ranks) {
    uint32_t type = 0;
    std::string out;
    if (!Request(MSG_FAILED, "", "", &type, &out) || type != MSG_VAL) {
      set_error(type == MSG_ERR ? out : "failed query failed");
      return false;
    }
    ranks->resize(out.size() / 4);
    if (!out.empty()) std::memcpy(ranks->data(), out.data(), out.size());
    return true;
  }

  void Close(bool leave) {
    bool expected = false;
    if (!closing_.compare_exchange_strong(expected, true)) return;
    hb_cv_.notify_all();
    if (fd_ >= 0 && leave) {
      // Best-effort graceful LEAVE, bounded on both the lock and the recv:
      // if the server died without FIN/RST the heartbeat thread may be
      // wedged in recv() holding req_mu_, and our own recv could block
      // forever — Close must terminate regardless. (Bounded try_lock poll,
      // not timed_mutex: TSAN does not model pthread_mutex_timedlock.)
      auto lock_deadline = Clock::now() + std::chrono::seconds(2);
      bool locked = false;
      while (!(locked = req_mu_.try_lock()) && Clock::now() < lock_deadline)
        std::this_thread::sleep_for(std::chrono::milliseconds(10));
      if (locked) {
        timeval tv{2, 0};
        ::setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        uint32_t type = 0;
        std::string rkey, rval;
        if (send_msg(fd_, MSG_LEAVE, "", ""))
          recv_msg(fd_, &type, &rkey, &rval);
        req_mu_.unlock();
      }
    }
    // Unblock a heartbeat thread stuck in recv() on a dead connection so
    // the join below cannot hang.
    if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
    if (hb_thread_.joinable()) hb_thread_.join();
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

 private:
  // One request/reply at a time on the shared socket (user calls and the
  // heartbeat thread interleave).
  bool Request(uint32_t type, const std::string& key, const std::string& val,
               uint32_t* rtype, std::string* rval) {
    std::lock_guard<std::mutex> lk(req_mu_);
    if (fd_ < 0) return false;
    std::string rkey;
    if (!send_msg(fd_, type, key, val)) return false;
    if (!recv_msg(fd_, rtype, &rkey, rval)) return false;
    return true;
  }

  int fd_ = -1;
  int32_t rank_ = -1;
  int32_t world_ = 0;
  std::mutex req_mu_;
  std::atomic<bool> closing_{false};
  std::thread hb_thread_;
  std::mutex hb_mu_;
  std::condition_variable hb_cv_;
};

}  // namespace

// ------------------------------------------------------------------- C ABI
extern "C" {

const char* nz_last_error() { return g_error.c_str(); }

void* nz_coord_start(int port, int world, int hb_timeout_ms) {
  auto* s = new CoordServer(port, world, hb_timeout_ms);
  if (!s->ok()) {
    set_error("bind/listen failed");
    delete s;
    return nullptr;
  }
  return s;
}

int nz_coord_port(void* s) { return static_cast<CoordServer*>(s)->port(); }

void nz_coord_stop(void* s) { delete static_cast<CoordServer*>(s); }

void* nz_client_connect(const char* host, int port, int rank_hint,
                        int timeout_ms, int hb_interval_ms) {
  auto* c = new CoordClient(host, port, rank_hint, timeout_ms, hb_interval_ms);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

int nz_client_rank(void* c) { return static_cast<CoordClient*>(c)->rank(); }
int nz_client_world(void* c) { return static_cast<CoordClient*>(c)->world(); }

int nz_client_put(void* c, const char* key, const void* val, long vlen) {
  return static_cast<CoordClient*>(c)->Put(
             key, std::string(static_cast<const char*>(val),
                              static_cast<size_t>(vlen)))
             ? 0
             : -1;
}

long nz_client_get(void* c, const char* key, void* out, long cap,
                   long timeout_ms) {
  std::string val;
  if (!static_cast<CoordClient*>(c)->Get(key, timeout_ms, &val)) return -1;
  long n = static_cast<long>(val.size());
  if (n <= cap && n > 0) std::memcpy(out, val.data(), val.size());
  return n;  // > cap means: retry with a bigger buffer
}

long nz_client_incr(void* c, const char* key) {
  return static_cast<long>(static_cast<CoordClient*>(c)->Incr(key));
}

int nz_client_barrier(void* c, long timeout_ms) {
  return static_cast<CoordClient*>(c)->Barrier(timeout_ms) ? 0 : -1;
}

long nz_client_failed(void* c, int* out, long cap) {
  std::vector<int32_t> ranks;
  if (!static_cast<CoordClient*>(c)->Failed(&ranks)) return -1;
  long n = static_cast<long>(ranks.size());
  for (long i = 0; i < n && i < cap; ++i) out[i] = ranks[i];
  return n;
}

void nz_client_leave(void* c) { static_cast<CoordClient*>(c)->Close(true); }

void nz_client_close(void* c) { delete static_cast<CoordClient*>(c); }

}  // extern "C"
