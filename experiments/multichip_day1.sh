#!/bin/bash
# Day-1 multi-chip recipe (VERDICT r4 item 9): the moment real multi-chip
# hardware appears, ONE command produces (a) the all-reduce bus-bandwidth
# metric of record (BASELINE.json `metric`) and (b) smoke runs of every
# mesh-axis path (tp / pp / sp / ep / zero1 / int8-wire) on real ICI.
#
#   bash experiments/multichip_day1.sh             # real devices
#   bash experiments/multichip_day1.sh --virtual 8 # CPU dry-run (no TPU)
#
# Outputs (committed by the operator or the wd committer):
#   artifacts/collectives_ici.json  — one JSON object per line:
#       {"collective": "all_reduce", "devices": N, "size_mb_per_dev": M,
#        "time_ms": T, "bus_gbps": B}
#     The metric of record is the LARGEST-size all_reduce row's bus_gbps.
#   artifacts/multichip_smoke.log   — one line per mode: loss + steps/s.
#
# Every path here is the same code the dryrun (__graft_entry__.py) runs on
# the virtual mesh every round — this script only exists so the first real
# pod session is a paste, not a design exercise.
set -eu
cd "$(dirname "$0")/.."
mkdir -p artifacts

VIRT=""
if [ "${1:-}" = "--virtual" ]; then
  VIRT="${2:?--virtual needs a device count}"
fi

NDEV="${VIRT:-$(python - <<'EOF'
import jax
print(len(jax.devices()))
EOF
)}"
if [ "$NDEV" -lt 2 ]; then
  echo "need >= 2 devices (got $NDEV); nothing to measure" >&2
  exit 1
fi
HALF=$((NDEV / 2))

PLAT=()
if [ -n "$VIRT" ]; then
  # nezha-train's --platform flag pins the CPU backend after jax import —
  # the env var alone cannot override the ambient axon site hook.
  PLAT=(--platform cpu)
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=$VIRT"
fi

echo "== collectives bus bandwidth ($NDEV devices) =="
if [ -n "$VIRT" ]; then
  python benchmarks/collectives.py --cpu-devices "$VIRT" \
    --sizes-mb 1 16 64 --iters 10 | tee artifacts/collectives_ici.json
else
  python benchmarks/collectives.py --sizes-mb 1 4 16 64 128 --iters 20 \
    | tee artifacts/collectives_ici.json
fi

echo "== mesh-axis smokes ==" | tee artifacts/multichip_smoke.log
FAILED=0
smoke() {  # $1 label, rest: nezha-train args
  local label="$1"; shift
  echo "-- $label" | tee -a artifacts/multichip_smoke.log
  local tmp rc=0
  tmp="$(mktemp)"
  # Capture to a file first so a crashed mode is recorded as FAIL with
  # its real traceback tail, not masked by the tee pipeline's status.
  python -m nezha_tpu.cli.train "$@" ${PLAT[@]+"${PLAT[@]}"} \
    --steps 3 --log-every 3 > "$tmp" 2>&1 || rc=$?
  if [ "$rc" -eq 0 ]; then
    tail -1 "$tmp" | tee -a artifacts/multichip_smoke.log
  else
    FAILED=1
    { echo "FAIL (rc=$rc): $label"; tail -5 "$tmp"; } \
      | tee -a artifacts/multichip_smoke.log
  fi
  rm -f "$tmp"
}

smoke "gspmd dp=${HALF} x tp=2"  --config gpt2_124m --model-preset tiny \
  --parallel gspmd --mesh "dp=${HALF},tp=2" --batch-size "$NDEV"
smoke "zero1 dp=${NDEV}"         --config bert_base_zero1 --model-preset tiny \
  --parallel zero1 --mesh "dp=${NDEV}" --batch-size "$NDEV"
smoke "zero1 int8 wire"          --config bert_base_zero1 --model-preset tiny \
  --parallel zero1 --mesh "dp=${NDEV}" --grad-allreduce int8 \
  --batch-size "$NDEV"
smoke "pp dp=${HALF} x pp=2"     --config gpt2_124m --model-preset tiny \
  --parallel pp --mesh "dp=${HALF},pp=2" --batch-size $((NDEV * 2)) \
  --microbatches 2
smoke "sp dp=${HALF} x sp=2"     --config gpt2_124m --model-preset tiny \
  --parallel sp --mesh "dp=${HALF},sp=2" --batch-size "$HALF"
smoke "moe ep dp=${HALF} x ep=2" --config gpt2_124m --model-preset tiny \
  --parallel gspmd --mesh "dp=${HALF},tp=1,ep=2" --moe-experts 4 \
  --batch-size "$NDEV"

if [ "$FAILED" -ne 0 ]; then
  echo "day-1 recipe: SOME SMOKES FAILED (see artifacts/multichip_smoke.log)"
  exit 1
fi
echo "day-1 recipe complete: artifacts/collectives_ici.json + multichip_smoke.log"
