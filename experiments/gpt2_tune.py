"""GPT-2 trunk tuning matrix (run on the real chip).

Round-2/3 established: loss path fused (+3%), flash attention tuned (+17%),
and the remaining gap to 50% MFU lives in the trunk (BENCH_NOTES.md r3:
head-free ceiling 128k tok/s). This script A/Bs the remaining trunk knobs
and prints one JSON line per variant:

  - ln:    xla composed layer norm vs the fused Pallas kernel (25 norms/step)
  - attn:  flash (default) sanity point vs xla composed
  - remat: per-block jax.checkpoint (the memory knob's throughput cost)
  - donate: buffer donation on/off (should be ~free, catches regressions)

Usage: python experiments/gpt2_tune.py [--steps 20] [--batch 8] [--seq 1024]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(variant: dict, batch: int, seq: int, steps: int,
            tiny: bool = False) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    small = dict(vocab_size=256, max_positions=max(seq, 64), num_layers=2,
                 num_heads=4, hidden_size=64) if tiny else {}
    cfg = GPT2Config(fused_loss_chunk=-1, **small, **variant.get("cfg", {}))
    model = GPT2(cfg, policy=bf16_policy())
    opt = optim.adamw(6e-4, weight_decay=0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss,
                           donate=variant.get("donate", True))

    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    b = {"tokens": jnp.asarray(tokens)}

    # bench.py's timing discipline (median-of-5 windows, host-fetch
    # barriers) — the levers here are few-% items, smaller than one-window
    # tunnel excursions.
    from bench import _time_steps
    sps, spread = _time_steps(step, state, b, steps, 60.0)
    tps = batch * seq * sps
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(
        state["variables"]["params"]))
    flops = (6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq) \
        * batch * seq
    return {"variant": variant["name"], "tokens_per_sec": round(tps, 1),
            "mfu": round(flops * sps / 197e12, 4),
            "spread": round(spread, 4)}


VARIANTS = [
    {"name": "baseline"},
    {"name": "ln_pallas", "cfg": {"ln_impl": "pallas"}},
    {"name": "scan", "cfg": {"scan_layers": True}},  # one-block trunk scan
    {"name": "attn_xla", "cfg": {"attn_impl": "xla"}},
    {"name": "remat", "cfg": {"remat": True}},  # cost of the memory knob
    {"name": "no_donate", "donate": False},
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--variants", nargs="+", default=None,
                    choices=[v["name"] for v in VARIANTS])
    ap.add_argument("--tiny", action="store_true",
                    help="seconds-scale model (CPU smoke of the harness; "
                         "numbers are meaningless)")
    args = ap.parse_args()
    if args.tiny:
        # Pin the CPU backend BEFORE any jax call: the env var alone is
        # not enough on the dev box (the ambient axon site hook overrides
        # backend selection, and its plugin init hangs when the TPU
        # tunnel is down — the exact situation --tiny exists for). Same
        # pattern as tests/conftest.py.
        import jax
        jax.config.update("jax_platforms", "cpu")
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    for v in VARIANTS:
        if args.variants and v["name"] not in args.variants:
            continue
        print(json.dumps(measure(v, args.batch, args.seq, args.steps,
                                 tiny=args.tiny)),
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
