"""Decompose the CLI MLP number (VERDICT r4 item 7: 2,330 ex/s at B=256
is ~9 steps/s — orders of magnitude below what a 3-layer MLP should do).

Prints one JSON line per measurement so the attribution is mechanical:

  - ping_ms:        round-trip of a trivial dispatch+fetch (tunnel RTT —
                    under axon every dispatch crosses a network tunnel).
  - bare_steps_ps:  jitted train step, batch staged on device ONCE,
                    async dispatch with a single trailing block — the
                    framework-free ceiling.
  - feed_steps_ps:  same step but a fresh host batch transferred every
                    step (the Trainer's pattern: next(batches) ->
                    jnp.asarray -> step).
  - loader_batches_ps: next(batches) alone (synthetic generator or MNIST
                    loader — whatever the CLI would use), no device work.
  - cli_examples_ps: the full CLI run (bench.py's bench_mlp), for
                    reference against the decomposition.

If bare >> feed ≈ cli, the cost is per-step host->device transfer (tunnel
bandwidth/latency); if ping_ms * steps accounts for the gap, it is pure
dispatch RTT; if loader is slow, it is the data path. The conclusion
belongs in BENCH_NOTES.md.

Usage: python experiments/mlp_probe.py [--steps 60] [--batch 256]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--cpu", action="store_true",
                    help="CPU-backend smoke of the harness itself")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import data, ops, optim
    from nezha_tpu.models.mlp import MLP
    from nezha_tpu.train.loop import init_train_state, make_train_step

    out = lambda **kw: print(json.dumps(kw), flush=True)

    # 1. Dispatch round-trip: trivial op, host fetch each call.
    x = jnp.zeros((), jnp.float32)
    add = jax.jit(lambda v: v + 1.0)
    add(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    n_ping = 30
    for _ in range(n_ping):
        x = add(x)
        x.block_until_ready()
    out(metric="ping_ms", value=round((time.perf_counter() - t0) / n_ping
                                      * 1e3, 3))

    # Mirror the CLI's mlp_mnist config exactly (model/opt/loss/data).
    model = MLP()
    opt = optim.momentum(0.1)
    ce = lambda logits, b: ops.softmax_cross_entropy_with_integer_labels(
        logits, b["label"]).mean()
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, ce)

    batches = data.mnist_batches(args.batch)
    host = next(batches)
    dev = {k: jnp.asarray(v) for k, v in host.items()}

    # 2. Bare step: device-resident batch, async dispatch, one final sync.
    # The step donates its state, so `s` threads through every loop below
    # (old handles are dead after each call).
    s, m = step(state, dev)
    jax.block_until_ready(m)  # compile + settle
    t0 = time.perf_counter()
    for _ in range(args.steps):
        s, m = step(s, dev)
    jax.block_until_ready(m)
    bare = args.steps / (time.perf_counter() - t0)
    out(metric="bare_steps_ps", value=round(bare, 2),
        examples_ps=round(bare * args.batch, 1))

    # 3. Fed step: fresh host batch transferred every step (Trainer
    #    pattern), async dispatch, one final sync.
    t0 = time.perf_counter()
    for _ in range(args.steps):
        fresh = {k: jnp.asarray(v) for k, v in host.items()}
        s, m = step(s, fresh)
    jax.block_until_ready(m)
    fed = args.steps / (time.perf_counter() - t0)
    out(metric="feed_steps_ps", value=round(fed, 2),
        examples_ps=round(fed * args.batch, 1))

    # 4. Loader alone (the same batches the CLI config would feed).
    t0 = time.perf_counter()
    for _ in range(args.steps):
        next(batches)
    out(metric="loader_batches_ps",
        value=round(args.steps / (time.perf_counter() - t0), 2))

    # 5. Full CLI for reference (bench.py's own config-1 path).
    from bench import bench_mlp
    on_tpu = jax.default_backend() == "tpu"
    out(metric="cli_examples_ps", value=round(bench_mlp(on_tpu), 1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
