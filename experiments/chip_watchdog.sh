#!/bin/bash
# Detached TPU-tunnel watchdog (round 4). The axon tunnel comes and goes;
# round 3 lost its entire measurement set to an outage. This loop probes
# every ~8 min and, whenever the tunnel answers, runs the next PENDING
# measurement steps (most valuable first, finest granularity) so even a
# short window banks real numbers. Each completed step drops a marker in
# artifacts/wd_done/ so a restart never redoes work.
#
# Launch:  nohup bash experiments/chip_watchdog.sh >> artifacts/watchdog.log 2>&1 &
# Outputs: artifacts/gpt2_tune_r04.jsonl, artifacts/rn50_variants_r04.jsonl,
#          artifacts/rn50_breakdown_r04.txt, artifacts/sp_smoke_r04.log
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts/wd_done

probe() {
  timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

run_step() {  # $1 marker, $2 timeout_s, rest: command (appends stdout to $3)
  local name="$1" tmo="$2" out="$3"; shift 3
  [ -e "artifacts/wd_done/$name" ] && return 0
  echo "$(date -u +%H:%M:%SZ) step $name START"
  if timeout "$tmo" "$@" >> "$out" 2>> "artifacts/wd_err_$name.log"; then
    touch "artifacts/wd_done/$name"
    echo "$(date -u +%H:%M:%SZ) step $name DONE"
    return 0
  fi
  echo "$(date -u +%H:%M:%SZ) step $name FAILED/TIMEOUT (will retry)"
  pkill -9 -f "experiments/gpt2_tune.py" 2>/dev/null
  pkill -9 -f "experiments/bert_ab.py" 2>/dev/null
  pkill -9 -f "experiments/rn50_probe.py" 2>/dev/null
  pkill -9 -f "nezha_tpu.cli.train" 2>/dev/null
  return 1
}

all_done() {
  for s in gpt2_ab bert_ab rn50_s2d_b256 gpt2_rest rn50_nodonate \
           rn50_probe rn50_stages sp_smoke longctx; do
    [ -e "artifacts/wd_done/$s" ] || return 1
  done
  return 0
}

while ! all_done; do
  if probe; then
    echo "$(date -u +%H:%M:%SZ) tunnel UP"
    run_step gpt2_ab 1500 artifacts/gpt2_tune_r04.jsonl \
      python experiments/gpt2_tune.py --variants baseline ln_pallas || continue
    run_step bert_ab 1500 artifacts/bert_ab_r04.jsonl \
      python experiments/bert_ab.py || continue
    run_step rn50_s2d_b256 1500 artifacts/rn50_variants_r04.jsonl \
      python experiments/rn50_probe.py --variants s2d b256 || continue
    run_step gpt2_rest 1800 artifacts/gpt2_tune_r04.jsonl \
      python experiments/gpt2_tune.py --variants attn_xla remat no_donate || continue
    run_step rn50_nodonate 1200 artifacts/rn50_variants_r04.jsonl \
      python experiments/rn50_probe.py --variants no_donate || continue
    run_step rn50_probe 1500 artifacts/rn50_breakdown_r04.txt \
      python experiments/rn50_probe.py --probe || continue
    run_step rn50_stages 1500 artifacts/rn50_stages_r04.txt \
      python experiments/rn50_probe.py --stages || continue
    run_step sp_smoke 1200 artifacts/sp_smoke_r04.log \
      python -m nezha_tpu.cli.train --config gpt2_124m --steps 3 \
        --batch-size 2 --seq-len 512 --parallel sp --mesh dp=1,sp=1 \
        --sp-flash on --log-every 1 || continue
    # Long-context single-chip: S=8192 with per-block remat + flash attn.
    # Second window's examples_per_sec excludes compile; x8192 = tokens/s.
    run_step longctx 1500 artifacts/longctx_r04.log \
      python -m nezha_tpu.cli.train --config gpt2_124m --steps 24 \
        --batch-size 1 --seq-len 8192 --remat --log-every 12 || continue
  else
    echo "$(date -u +%H:%M:%SZ) probe failed/hung"
  fi
  sleep 480
done
echo "$(date -u +%H:%M:%SZ) ALL MEASUREMENT STEPS DONE"
