#!/bin/bash
# Detached TPU-tunnel watchdog (round 5). The axon tunnel comes and goes
# (r3: total outage; r4: one 20-min window in ~20 h). This loop probes
# every ~8 min and, whenever the tunnel answers, runs the PENDING
# measurement steps in value order so even a short window banks real
# numbers. Each completed step drops a marker in artifacts/wd_done_r05/ so a
# restart never redoes work.
#
# Hardening (r4 review findings + r4 advisor):
# - step stdout goes to a temp file and is appended to the banked artifact
#   only on rc=0 — a timeout can't leave truncated/duplicate JSON lines;
# - a step failing repeatedly (3x) is given up (marker *.givenup) instead
#   of starving every later step in a tight retry loop;
# - after any failure the tunnel is re-probed before the next step so a
#   dead tunnel ends the pass instead of burning the remaining steps;
# - each step runs under setsid in its own process group and cleanup
#   kills THAT group only (kill -9 -- -PID) — no pkill pattern matching
#   that could hit an operator's concurrent run (ADVICE r4).
#
# Round-5 queue rationale (VERDICT r4 "Next round"):
# 1. rn50_stages — per-stage traffic probe, the round-5 headline
#    diagnosis (never run on chip).
# 2. bench_full — full bench.py as the measurement of record (r4's
#    driver bench failed; the builder-banked run saved the round).
# 3. gpt2_ab / bert_ab — flip ln_impl / attn defaults on evidence.
# 4. the rest: rn50 variants, gpt2 trunk levers, mlp profile, graph-IR
#    GPT-2 vs module engine, sp smoke, long-context point.
#
# Launch:  nohup bash experiments/chip_watchdog.sh >> artifacts/watchdog_r05.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts/wd_done_r05

STEPS=(rn50_stages bench_full gpt2_ab bert_ab rn50_s2d_b256 rn50_remat gpt2_scan
       gpt2_rest mlp_profile graph_gpt2 rn50_nodonate rn50_probe
       sp_smoke longctx)

probe() {
  timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1
}

step_cmd() {  # $1 step -> echoes "timeout_s|artifact|command..."
  case "$1" in
    rn50_stages)   echo "1500|artifacts/rn50_stages_r05.txt|python experiments/rn50_probe.py --stages" ;;
    bench_full)    echo "2400|artifacts/bench_r05_live.json|python bench.py" ;;
    gpt2_ab)       echo "1500|artifacts/gpt2_tune_r05.jsonl|python experiments/gpt2_tune.py --variants baseline ln_pallas" ;;
    bert_ab)       echo "1500|artifacts/bert_ab_r05.jsonl|python experiments/bert_ab.py" ;;
    rn50_s2d_b256) echo "1500|artifacts/rn50_variants_r05.jsonl|python experiments/rn50_probe.py --variants s2d b256" ;;
    rn50_remat)    echo "1500|artifacts/rn50_variants_r05.jsonl|python experiments/rn50_probe.py --variants remat remat_b256" ;;
    gpt2_scan)     echo "1500|artifacts/gpt2_tune_r05.jsonl|python experiments/gpt2_tune.py --variants scan" ;;
    gpt2_rest)     echo "1800|artifacts/gpt2_tune_r05.jsonl|python experiments/gpt2_tune.py --variants attn_xla remat no_donate" ;;
    mlp_profile)   echo "900|artifacts/mlp_profile_r05.txt|python experiments/mlp_probe.py" ;;
    graph_gpt2)    echo "1500|artifacts/graph_gpt2_r05.jsonl|python experiments/graph_bench.py" ;;
    rn50_nodonate) echo "1200|artifacts/rn50_variants_r05.jsonl|python experiments/rn50_probe.py --variants no_donate" ;;
    rn50_probe)    echo "1500|artifacts/rn50_breakdown_r05.txt|python experiments/rn50_probe.py --probe" ;;
    sp_smoke)      echo "1200|artifacts/sp_smoke_r05.log|python -m nezha_tpu.cli.train --config gpt2_124m --steps 3 --batch-size 2 --seq-len 512 --parallel sp --mesh dp=1,sp=1 --sp-flash on --log-every 1" ;;
    longctx)       echo "1500|artifacts/longctx_r05.log|python -m nezha_tpu.cli.train --config gpt2_124m --steps 24 --batch-size 1 --seq-len 8192 --remat --log-every 12" ;;
  esac
}

resolved() {  # done or given up
  [ -e "artifacts/wd_done_r05/$1" ] || [ -e "artifacts/wd_done_r05/$1.givenup" ]
}

all_resolved() {
  for s in "${STEPS[@]}"; do resolved "$s" || return 1; done
  return 0
}

run_step() {  # $1 step name; returns 0 ok, 1 failed
  local name="$1" spec tmo out cmd
  spec="$(step_cmd "$name")"
  tmo="${spec%%|*}"; spec="${spec#*|}"
  out="${spec%%|*}"; cmd="${spec#*|}"
  local tmp="artifacts/.wd_tmp_$name"
  echo "$(date -u +%H:%M:%SZ) step $name START"
  # setsid: the child leads its own process group so cleanup can kill
  # exactly that group (grandchildren included) and nothing else.
  setsid timeout "$tmo" $cmd > "$tmp" 2>> "artifacts/wd_err_$name.log" &
  local pid=$!
  if wait "$pid"; then
    cat "$tmp" >> "$out"
    rm -f "$tmp"
    touch "artifacts/wd_done_r05/$name"
    echo "$(date -u +%H:%M:%SZ) step $name DONE"
    return 0
  fi
  kill -9 -- "-$pid" 2>/dev/null
  rm -f "$tmp"
  local fails_file="artifacts/wd_done_r05/.fails_$name"
  local fails=$(( $(cat "$fails_file" 2>/dev/null || echo 0) + 1 ))
  echo "$fails" > "$fails_file"
  if [ "$fails" -ge 3 ]; then
    touch "artifacts/wd_done_r05/$name.givenup"
    echo "$(date -u +%H:%M:%SZ) step $name GIVEN UP after $fails failures"
  else
    echo "$(date -u +%H:%M:%SZ) step $name FAILED ($fails/3, will retry)"
  fi
  return 1
}

while ! all_resolved; do
  if probe; then
    echo "$(date -u +%H:%M:%SZ) tunnel UP"
    for s in "${STEPS[@]}"; do
      resolved "$s" && continue
      if ! run_step "$s"; then
        # Distinguish "step is broken" from "tunnel died mid-step": only
        # continue down the list while the tunnel still answers.
        if ! probe; then
          echo "$(date -u +%H:%M:%SZ) tunnel lost mid-pass"
          break
        fi
      fi
    done
  else
    echo "$(date -u +%H:%M:%SZ) probe failed/hung"
  fi
  all_resolved && break
  sleep 480
done
echo "$(date -u +%H:%M:%SZ) ALL MEASUREMENT STEPS RESOLVED"
