"""RN50 perf probe + tuning matrix (run on the real chip).

Round-3 landed two structural fixes proven equivalent by test but never
measured on hardware (the tunnel died): the space-to-depth stem and
compute-dtype BatchNorm. Round 4 adds the next levers from the r3 roofline
(BENCH_NOTES.md: 51 GB/step HLO bytes-accessed — bandwidth-heavy): buffer
donation on the train state and batch 256. This script measures them all.

Default mode prints one JSON line per variant (median-of-3 windows):

  baseline   conv7 stem, B=128, donated state (the r2 bench geometry)
  s2d        space-to-depth stem (r3 fix #1; expected ~3.5 ms of the 5 ms
             stem per the r3 utilization probe)
  no_donate  donation off (costs a full param+opt-state copy per step if
             XLA can't reuse; quantifies what donation buys)
  b256       s2d + batch 256 (amortizes fixed costs; bigger MXU tiles)
  remat      per-bottleneck jax.checkpoint (trade saved-activation HBM
             reads for recompute FLOPs — wins iff bandwidth-bound)

``--probe`` runs the r3 breakdown instead (fwd / fwd+bwd / stem-alone /
XLA cost analysis) for roofline arithmetic.

Usage: python experiments/rn50_probe.py [--steps 10] [--variants s2d ...]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PEAK_FLOPS = 197e12  # v5e bf16


IMAGE_SIZE = 224  # overridable via --image-size for CPU smoke runs


def _build(stem: str, batch: int, donate: bool,
           remat: bool = False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import ops, optim
    from nezha_tpu.models.resnet import resnet50
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    model = resnet50(stem=stem, remat=remat, policy=bf16_policy())
    opt = optim.momentum(0.1, beta=0.9, weight_decay=1e-4)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
        logits, b_["label"]).mean()
    step = make_train_step(model, opt, ce, donate=donate)
    rng = np.random.RandomState(0)
    sz = IMAGE_SIZE
    b = {"image": jnp.asarray(rng.rand(batch, sz, sz, 3).astype(np.float32)),
         "label": jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)}
    return step, state, b


def measure(variant: dict, steps: int) -> dict:
    batch = variant.get("batch", 128)
    step, state, b = _build(variant.get("stem", "conv7"), batch,
                            variant.get("donate", True),
                            variant.get("remat", False))
    # ONE AOT compile serves both the timing loop and the cost analysis
    # (a second compile per geometry would double chip time and hold a
    # duplicate state in HBM alongside the donated one — b256 could OOM).
    compiled = step.lower(state, b).compile()
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = cost.get("flops") or None
    except Exception:
        pass
    # bench.py's timing discipline (median-of-5 windows, host-fetch
    # barriers; state threads through, so donation stays legal).
    from bench import _time_steps
    sps, spread = _time_steps(compiled, state, b, steps, 90.0)
    return {"variant": variant["name"], "batch": batch,
            "images_per_sec": round(batch * sps, 1),
            "mfu": round(flops * sps / PEAK_FLOPS, 4) if flops else None,
            "spread": round(spread, 4)}


VARIANTS = [
    {"name": "baseline", "stem": "conv7"},
    {"name": "s2d", "stem": "s2d"},
    {"name": "no_donate", "stem": "s2d", "donate": False},
    {"name": "b256", "stem": "s2d", "batch": 256},
    # r5 bandwidth hypothesis: recompute each bottleneck in backward
    # instead of reading saved intermediates — if the step is truly bound
    # on saved-activation traffic (51 GB/step HLO vs 19.8 GB analytic
    # floor), remat should WIN despite +~30% conv FLOPs.
    {"name": "remat", "stem": "s2d", "remat": True},
    {"name": "remat_b256", "stem": "s2d", "remat": True, "batch": 256},
]


def probe() -> None:
    """The r3 breakdown: where does the step go? (roofline inputs)"""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import nn, ops
    from nezha_tpu.models.resnet import resnet50
    from nezha_tpu.tensor import bf16_policy

    B = 128
    step, state, b = _build("conv7", B, donate=False)
    model = resnet50(policy=bf16_policy())
    ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
        logits, b_["label"]).mean()

    def timeit(fn, *args, n=10, fetch=None):
        out = fn(*args)
        if fetch:
            fetch(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        if fetch:
            fetch(out)
        return (time.perf_counter() - t0) / n, out

    compiled = jax.jit(step).lower(state, b).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    print("XLA flops/step:", cost.get("flops"),
          " bytes:", cost.get("bytes accessed"))
    dt, _ = timeit(lambda: compiled(state, b), n=10,
                   fetch=lambda o: float(o[1]["loss"]))
    print(f"full step: {dt*1e3:.2f} ms -> {B/dt:.0f} img/s "
          f"MFU(XLA)={cost.get('flops', 0)/dt/PEAK_FLOPS:.3f}")

    fwd = jax.jit(lambda v, bb: model.apply(v, bb, training=True)[0].sum()
                  ).lower(state["variables"], b).compile()
    dt_f, _ = timeit(lambda: fwd(state["variables"], b), n=10,
                     fetch=float)
    print(f"fwd only: {dt_f*1e3:.2f} ms")

    def loss_fn(params, variables, bb):
        v = dict(variables)
        v["params"] = params
        logits, _ = model.apply(v, bb, training=True)
        return ce(logits, bb)

    g = jax.jit(jax.grad(loss_fn)).lower(
        state["variables"]["params"], state["variables"], b).compile()
    dt_g, _ = timeit(
        lambda: g(state["variables"]["params"], state["variables"], b),
        n=10, fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].sum()))
    print(f"fwd+bwd: {dt_g*1e3:.2f} ms (optimizer+rest: "
          f"{(dt - dt_g)*1e3:.2f} ms)")

    stem = nn.Conv2d(3, 64, 7, stride=2, use_bias=False,
                     policy=bf16_policy())
    sv = stem.init(jax.random.PRNGKey(1))

    def stem_loss(p, x):
        v = dict(sv)
        v["params"] = p
        y, _ = stem.apply(v, x)
        return jnp.sum(jnp.asarray(y, jnp.float32))

    gs = jax.jit(jax.grad(stem_loss)).lower(sv["params"], b["image"]
                                            ).compile()
    dt_s, _ = timeit(
        lambda: gs(sv["params"], b["image"]), n=20,
        fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].sum()))
    print(f"stem conv fwd+bwd: {dt_s*1e3:.2f} ms")


def stages(batch: int = 128) -> None:
    """Per-stage fwd+bwd time AND HLO bytes-accessed (default B=128, s2d).

    The r3/r4 whole-step numbers say "bandwidth-bound somewhere"; this
    ranks the four bottleneck stages + stem + head so the traffic work
    aims at the hungriest stage instead of the whole network.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import nn, ops
    from nezha_tpu.models.resnet import resnet50
    from nezha_tpu.nn.module import run_child
    from nezha_tpu.tensor import bf16_policy

    B, size = batch, IMAGE_SIZE
    model = resnet50(stem="s2d", policy=bf16_policy())
    variables = model.init(jax.random.PRNGKey(0))

    sizes, idx, groups = (3, 4, 6, 3), 0, []
    for n in sizes:
        groups.append(list(range(idx, idx + n)))
        idx += n
    s4 = size // 4
    in_shapes = [(B, s4, s4, 64), (B, s4, s4, 256),
                 (B, s4 // 2, s4 // 2, 512), (B, s4 // 4, s4 // 4, 1024)]

    def timed_grad(f, *args, n=10):
        """compile f's grad (wrt all args), time it, report ms + HLO GB."""
        g = jax.jit(jax.grad(f, argnums=tuple(range(len(args)))))
        compiled = g.lower(*args).compile()
        gb = None
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            gb = cost.get("bytes accessed", 0) / 1e9
        except Exception:
            pass
        out = compiled(*args)
        float(jax.tree_util.tree_leaves(out)[0].sum())
        t0 = time.perf_counter()
        for _ in range(n):
            out = compiled(*args)
        float(jax.tree_util.tree_leaves(out)[0].sum())
        return (time.perf_counter() - t0) / n * 1e3, gb

    rng = np.random.RandomState(0)
    img = jnp.asarray(rng.rand(B, size, size, 3).astype(np.float32))

    def stem_f(params, x):
        v = {"params": params, "state": variables["state"]}
        states: dict = {}
        from nezha_tpu.models.resnet import _space_to_depth_stem
        pol = model.stem_conv.policy
        y = _space_to_depth_stem(pol.cast_to_compute(x),
                                 pol.cast_to_compute(params["stem_conv"]["w"]))
        y = run_child(model.stem_bn, "stem_bn", v, states, y, training=True)
        y = jnp.maximum(y, 0)
        return jnp.sum(jnp.asarray(nn.max_pool(y, 3, 2, "SAME"), jnp.float32))

    ms, gb = timed_grad(stem_f, variables["params"], img)
    print(f"stem(s2d)+bn+pool : {ms:7.2f} ms  {gb and f'{gb:6.1f} GB'}")

    for s, g in enumerate(groups):
        x = jnp.asarray(rng.rand(*in_shapes[s]).astype(np.float32),
                        jnp.bfloat16)

        def stage_f(params, xin, _g=tuple(g)):
            v = {"params": params, "state": variables["state"]}
            states: dict = {}
            out = xin
            for i in _g:
                out = run_child(model.blocks[i], f"blocks{i}", v, states,
                                out, training=True)
            return jnp.sum(jnp.asarray(out, jnp.float32))

        ms, gb = timed_grad(stage_f, variables["params"], x)
        print(f"stage{s + 1} ({len(g)} blocks) : {ms:7.2f} ms  "
              f"{gb and f'{gb:6.1f} GB'}")

    xh = jnp.asarray(
        rng.rand(B, s4 // 8, s4 // 8, 2048).astype(np.float32),
        jnp.bfloat16)
    lbl = jnp.asarray(rng.randint(0, 1000, B), jnp.int32)

    def head_f(params, xin):
        v = {"params": params, "state": variables["state"]}
        states: dict = {}
        pooled = nn.global_avg_pool(xin)
        logits = run_child(model.head, "head", v, states, pooled,
                           training=True)
        return ops.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), lbl).mean()

    ms, gb = timed_grad(head_f, variables["params"], xh)
    print(f"pool+head+CE      : {ms:7.2f} ms  {gb and f'{gb:6.1f} GB'}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--probe", action="store_true",
                    help="run the step-breakdown probe instead of the "
                         "variant matrix")
    ap.add_argument("--stages", action="store_true",
                    help="per-stage fwd+bwd time + HLO bytes (traffic "
                         "ranking)")
    ap.add_argument("--variants", nargs="+", default=None,
                    choices=[v["name"] for v in VARIANTS])
    ap.add_argument("--image-size", type=int, default=224,
                    help="input size (shrink for CPU smoke runs)")
    ap.add_argument("--base-batch", type=int, default=None,
                    help="override every variant's batch (CPU smoke)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (harness smoke during TPU "
                         "tunnel outages; env vars alone cannot override "
                         "the ambient axon plugin — see gpt2_tune --tiny)")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    global IMAGE_SIZE
    IMAGE_SIZE = args.image_size
    if args.base_batch:
        for v in VARIANTS:
            v["batch"] = args.base_batch
    if args.probe:
        probe()
        return 0
    if args.stages:
        stages(batch=args.base_batch or 128)
        return 0
    for v in VARIANTS:
        if args.variants and v["name"] not in args.variants:
            continue
        print(json.dumps(measure(v, args.steps)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
