"""RN50 perf probe: where does the step time go on the real chip?"""
import os, time, json, sys
import jax, jax.numpy as jnp, numpy as np
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from nezha_tpu import ops, optim
from nezha_tpu.models.resnet import resnet50
from nezha_tpu.tensor import bf16_policy
from nezha_tpu.train.loop import init_train_state, make_train_step

B, SZ = 128, 224
model = resnet50(policy=bf16_policy())
opt = optim.momentum(0.1, beta=0.9, weight_decay=1e-4)
state = init_train_state(model, opt, jax.random.PRNGKey(0))
ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
    logits, b_["label"]).mean()
step = make_train_step(model, opt, ce)
rng = np.random.RandomState(0)
b = {"image": jnp.asarray(rng.rand(B, SZ, SZ, 3).astype(np.float32)),
     "label": jnp.asarray(rng.randint(0, 1000, B), jnp.int32)}

def timeit(fn, *args, n=10, fetch=None):
    out = fn(*args)
    if fetch: fetch(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    if fetch: fetch(out)
    return (time.perf_counter() - t0) / n, out

compiled = jax.jit(step, donate_argnums=(0,)).lower(state, b).compile()
cost = compiled.cost_analysis()
if isinstance(cost, (list, tuple)): cost = cost[0]
print("XLA flops/step:", cost.get("flops"), " bytes:", cost.get("bytes accessed"))
# donation means we must rebuild state each call — time without donation instead
step_nd = jax.jit(step).lower(state, b).compile()
dt, out = timeit(lambda: step_nd(state, b), n=10, fetch=lambda o: float(o[1]["loss"]))
print(f"full step: {dt*1e3:.2f} ms  -> {B/dt:.0f} img/s  MFU(XLA)={cost.get('flops',0)/dt/197e12:.3f}")

# forward only (train mode, incl BN stats)
fwd = jax.jit(lambda v, bb: model.apply(v, bb, training=True)[0].sum()).lower(state["variables"], b).compile()
dt_f, _ = timeit(lambda: fwd(state["variables"], b), n=10, fetch=lambda o: float(o))
print(f"fwd only: {dt_f*1e3:.2f} ms")

# fwd+bwd (no optimizer)
def loss_fn(params, variables, bb):
    v = dict(variables); v["params"] = params
    logits, _ = model.apply(v, bb, training=True)
    return ce(logits, bb)
g = jax.jit(jax.grad(loss_fn)).lower(state["variables"]["params"], state["variables"], b).compile()
dt_g, _ = timeit(lambda: g(state["variables"]["params"], state["variables"], b), n=10,
                 fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].sum()))
print(f"fwd+bwd: {dt_g*1e3:.2f} ms  (optimizer+rest: {(dt-dt_g)*1e3:.2f} ms)")

# stem alone (7x7s2 conv fwd+bwd) at step scale
from nezha_tpu import nn
stem = nn.Conv2d(3, 64, 7, stride=2, use_bias=False, policy=bf16_policy())
sv = stem.init(jax.random.PRNGKey(1))
def stem_loss(p, x):
    v = dict(sv); v["params"] = p
    y, _ = stem.apply(v, x)
    return jnp.sum(jnp.asarray(y, jnp.float32))
gs = jax.jit(jax.grad(stem_loss)).lower(sv["params"], b["image"]).compile()
dt_s, _ = timeit(lambda: gs(sv["params"], b["image"]), n=20,
                 fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].sum()))
print(f"stem conv fwd+bwd: {dt_s*1e3:.2f} ms")
