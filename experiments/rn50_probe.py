"""RN50 perf probe + tuning matrix (run on the real chip).

Round-3 landed two structural fixes proven equivalent by test but never
measured on hardware (the tunnel died): the space-to-depth stem and
compute-dtype BatchNorm. Round 4 adds the next levers from the r3 roofline
(BENCH_NOTES.md: 51 GB/step HLO bytes-accessed — bandwidth-heavy): buffer
donation on the train state and batch 256. This script measures them all.

Default mode prints one JSON line per variant (median-of-3 windows):

  baseline   conv7 stem, B=128, donated state (the r2 bench geometry)
  s2d        space-to-depth stem (r3 fix #1; expected ~3.5 ms of the 5 ms
             stem per the r3 utilization probe)
  no_donate  donation off (costs a full param+opt-state copy per step if
             XLA can't reuse; quantifies what donation buys)
  b256       s2d + batch 256 (amortizes fixed costs; bigger MXU tiles)

``--probe`` runs the r3 breakdown instead (fwd / fwd+bwd / stem-alone /
XLA cost analysis) for roofline arithmetic.

Usage: python experiments/rn50_probe.py [--steps 10] [--variants s2d ...]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PEAK_FLOPS = 197e12  # v5e bf16


IMAGE_SIZE = 224  # overridable via --image-size for CPU smoke runs


def _build(stem: str, batch: int, donate: bool):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import ops, optim
    from nezha_tpu.models.resnet import resnet50
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    model = resnet50(stem=stem, policy=bf16_policy())
    opt = optim.momentum(0.1, beta=0.9, weight_decay=1e-4)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
        logits, b_["label"]).mean()
    step = make_train_step(model, opt, ce, donate=donate)
    rng = np.random.RandomState(0)
    sz = IMAGE_SIZE
    b = {"image": jnp.asarray(rng.rand(batch, sz, sz, 3).astype(np.float32)),
         "label": jnp.asarray(rng.randint(0, 1000, batch), jnp.int32)}
    return step, state, b


def measure(variant: dict, steps: int) -> dict:
    batch = variant.get("batch", 128)
    step, state, b = _build(variant.get("stem", "conv7"), batch,
                            variant.get("donate", True))
    # ONE AOT compile serves both the timing loop and the cost analysis
    # (a second compile per geometry would double chip time and hold a
    # duplicate state in HBM alongside the donated one — b256 could OOM).
    compiled = step.lower(state, b).compile()
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = cost.get("flops") or None
    except Exception:
        pass
    # Threading state through the loop keeps donation legal (each step
    # consumes the previous step's output buffers).
    state, m = compiled(state, b)
    state, m = compiled(state, b)
    float(m["loss"])
    rates = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = compiled(state, b)
        float(m["loss"])
        rates.append(steps / (time.perf_counter() - t0))
    rates.sort()
    return {"variant": variant["name"], "batch": batch,
            "images_per_sec": round(batch * rates[1], 1),
            "mfu": round(flops * rates[1] / PEAK_FLOPS, 4)
            if flops else None,
            "spread": round((rates[-1] - rates[0]) / rates[1], 4)}


VARIANTS = [
    {"name": "baseline", "stem": "conv7"},
    {"name": "s2d", "stem": "s2d"},
    {"name": "no_donate", "stem": "s2d", "donate": False},
    {"name": "b256", "stem": "s2d", "batch": 256},
]


def probe() -> None:
    """The r3 breakdown: where does the step go? (roofline inputs)"""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import nn, ops
    from nezha_tpu.models.resnet import resnet50
    from nezha_tpu.tensor import bf16_policy

    B = 128
    step, state, b = _build("conv7", B, donate=False)
    model = resnet50(policy=bf16_policy())
    ce = lambda logits, b_: ops.softmax_cross_entropy_with_integer_labels(
        logits, b_["label"]).mean()

    def timeit(fn, *args, n=10, fetch=None):
        out = fn(*args)
        if fetch:
            fetch(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        if fetch:
            fetch(out)
        return (time.perf_counter() - t0) / n, out

    compiled = jax.jit(step).lower(state, b).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0]
    print("XLA flops/step:", cost.get("flops"),
          " bytes:", cost.get("bytes accessed"))
    dt, _ = timeit(lambda: compiled(state, b), n=10,
                   fetch=lambda o: float(o[1]["loss"]))
    print(f"full step: {dt*1e3:.2f} ms -> {B/dt:.0f} img/s "
          f"MFU(XLA)={cost.get('flops', 0)/dt/PEAK_FLOPS:.3f}")

    fwd = jax.jit(lambda v, bb: model.apply(v, bb, training=True)[0].sum()
                  ).lower(state["variables"], b).compile()
    dt_f, _ = timeit(lambda: fwd(state["variables"], b), n=10,
                     fetch=float)
    print(f"fwd only: {dt_f*1e3:.2f} ms")

    def loss_fn(params, variables, bb):
        v = dict(variables)
        v["params"] = params
        logits, _ = model.apply(v, bb, training=True)
        return ce(logits, bb)

    g = jax.jit(jax.grad(loss_fn)).lower(
        state["variables"]["params"], state["variables"], b).compile()
    dt_g, _ = timeit(
        lambda: g(state["variables"]["params"], state["variables"], b),
        n=10, fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].sum()))
    print(f"fwd+bwd: {dt_g*1e3:.2f} ms (optimizer+rest: "
          f"{(dt - dt_g)*1e3:.2f} ms)")

    stem = nn.Conv2d(3, 64, 7, stride=2, use_bias=False,
                     policy=bf16_policy())
    sv = stem.init(jax.random.PRNGKey(1))

    def stem_loss(p, x):
        v = dict(sv)
        v["params"] = p
        y, _ = stem.apply(v, x)
        return jnp.sum(jnp.asarray(y, jnp.float32))

    gs = jax.jit(jax.grad(stem_loss)).lower(sv["params"], b["image"]
                                            ).compile()
    dt_s, _ = timeit(
        lambda: gs(sv["params"], b["image"]), n=20,
        fetch=lambda o: float(jax.tree_util.tree_leaves(o)[0].sum()))
    print(f"stem conv fwd+bwd: {dt_s*1e3:.2f} ms")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--probe", action="store_true",
                    help="run the step-breakdown probe instead of the "
                         "variant matrix")
    ap.add_argument("--variants", nargs="+", default=None,
                    choices=[v["name"] for v in VARIANTS])
    ap.add_argument("--image-size", type=int, default=224,
                    help="input size (shrink for CPU smoke runs)")
    ap.add_argument("--base-batch", type=int, default=None,
                    help="override every variant's batch (CPU smoke)")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (harness smoke during TPU "
                         "tunnel outages; env vars alone cannot override "
                         "the ambient axon plugin — see gpt2_tune --tiny)")
    args = ap.parse_args()
    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    global IMAGE_SIZE
    IMAGE_SIZE = args.image_size
    if args.base_batch:
        for v in VARIANTS:
            v["batch"] = args.base_batch
    if args.probe:
        probe()
        return 0
    for v in VARIANTS:
        if args.variants and v["name"] not in args.variants:
            continue
        print(json.dumps(measure(v, args.steps)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
