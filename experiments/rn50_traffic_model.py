"""Analytic HBM-traffic floor for the RN50 train step (B=128, 224px, bf16).

Pairs with `rn50_probe.py --stages` (measured per-stage ms + HLO
bytes-accessed): the ratio measured/analytic per stage says where XLA's
lowering spends bandwidth above the model's own needs (e.g. conv-backward
transpose materialization), and how much of the step is irreducible at
this geometry. Pure arithmetic — runs anywhere, no jax needed.

Model of one training step per tensor X of a conv/BN/relu chain:
  fwd:  conv writes X once; BN reads X for stats, reads X again for
        normalize, writes Y; relu/residual fuse into the BN write.
  bwd:  dX chain: read dY, read saved activations (conv input for dW and
        dX, BN input for its backward), write dX. Counted as: each saved
        activation read twice (dW + dX paths), each gradient tensor
        written once and read once.
Weights + optimizer: momentum fp32 (25.6M params): read w, read m, write
both, plus bf16 cast write/read per step.
"""

B = 128
BPE = 2  # bf16

# (H, W, C_out) of every conv output in RN50 at 224px input, s2d stem.
# Bottleneck stage s: [1x1 C, 3x3 C, 1x1 4C] x blocks, C = 64*2^s.
def stage_tensors():
    stages = []
    # stem: s2d conv output 112x112x64, maxpool out 56x56x64
    stages.append(("stem", [(112, 112, 64), (56, 56, 64)]))
    sizes = {0: (56, 3), 1: (28, 4), 2: (14, 6), 3: (7, 3)}
    for s, (hw, blocks) in sizes.items():
        c = 64 * (2 ** s)
        t = []
        for b in range(blocks):
            # downsample conv in block 0 of stages 1-3 runs at the OUT res
            t += [(hw, hw, c), (hw, hw, c), (hw, hw, 4 * c)]
            if b == 0:
                t += [(hw, hw, 4 * c)]  # projection shortcut
        stages.append((f"stage{s + 1}", t))
    return stages


def gb(n):
    return n * B * BPE / 1e9


def main():
    total = 0.0
    print(f"analytic HBM floor, B={B} bf16 (GB/step)")
    print(f"{'stage':8} {'fwd_write':>9} {'fwd_read':>8} {'bwd':>8} "
          f"{'total':>8}")
    for name, tensors in stage_tensors():
        elems = sum(h * w * c for h, w, c in tensors)
        fwd_w = gb(elems)            # conv/BN outputs written once
        fwd_r = gb(elems) * 2        # BN stats + normalize reads
        # bwd: read dY once + saved acts twice (dW, dX), write dX once
        bwd = gb(elems) * 4
        t = fwd_w + fwd_r + bwd
        total += t
        print(f"{name:8} {fwd_w:9.2f} {fwd_r:8.2f} {bwd:8.2f} {t:8.2f}")
    # params: 25.6M; momentum fp32: read w,m + write w,m (4B each) + bf16
    # compute copy write+read
    p = 25.6e6
    opt = (4 * p * 4 + 2 * p * 2) / 1e9
    total += opt
    print(f"{'opt/w':8} {'':9} {'':8} {'':8} {opt:8.2f}")
    print(f"{'TOTAL':8} {'':9} {'':8} {'':8} {total:8.2f}")
    print()
    print("vs v5e HBM ~819 GB/s:", f"{total / 819 * 1e3:.1f} ms/step floor",
          f"= {B / (total / 819):.0f} img/s ceiling (bandwidth-only)")


if __name__ == "__main__":
    main()
