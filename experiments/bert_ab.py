"""BERT A/B on the real chip: (1) fp32 dense logits vs the fused
bf16-logsumexp head (BertConfig.fused_loss_chunk=-1), (2) composed XLA
attention vs the non-causal Pallas flash kernel (BertConfig.attn_impl).

The fp32 [16,512,30522] logit tensor is ~1 GB written+read per step at the
bench geometry (GPT-2's identical fusion measured +3%); the S=512
bidirectional score tensors are ~100 MB/layer/direction (GPT-2's flash
measured +17% e2e at S=1024 causal). One JSON line per variant
(median-of-3 windows), same timing discipline as bench.py.

Usage: python experiments/bert_ab.py [--steps 10] [--tiny]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


VARIANTS = [
    # r2-r4 bench configuration (the 117.5k tok/s morning-of-r4 number)
    {"name": "dense_fp32", "cfg": {"fused_loss_chunk": 0,
                                   "attn_impl": "xla"}},
    # fused bf16-logit CE alone
    {"name": "fused", "cfg": {"fused_loss_chunk": -1, "attn_impl": "xla"}},
    # + non-causal flash attention (the new TPU default)
    {"name": "fused_flash", "cfg": {"fused_loss_chunk": -1,
                                    "attn_impl": "flash"}},
    # + scan-over-layers encoder (r5 trunk lever; parity-tested)
    {"name": "fused_flash_scan", "cfg": {"fused_loss_chunk": -1,
                                         "attn_impl": "flash",
                                         "scan_layers": True}},
    # + fused Pallas layer norms (26 norms/step at BERT-base geometry)
    {"name": "fused_flash_ln", "cfg": {"fused_loss_chunk": -1,
                                       "attn_impl": "flash",
                                       "ln_impl": "pallas"}},
]


def measure(variant: dict, steps: int, tiny: bool) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nezha_tpu import optim
    from nezha_tpu.models.bert import Bert, BertConfig, mlm_loss
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.train.loop import init_train_state, make_train_step

    batch, seq = (2, 64) if tiny else (16, 512)
    kw = dict(num_layers=2) if tiny else {}
    cfg = BertConfig(**variant["cfg"], **kw)
    model = Bert(cfg, policy=bf16_policy())
    opt = optim.adamw(1e-4, weight_decay=0.01)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, mlm_loss)

    r = np.random.RandomState(0)
    tokens = r.randint(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.full_like(tokens, -100)
    mask = r.rand(batch, seq) < 0.15
    labels[mask] = tokens[mask]
    # No padding_mask: full-length batches; its all-True mask would force
    # composed-XLA attention off the flash path (BertConfig.attn_impl).
    b = {"tokens": jnp.asarray(tokens), "labels": jnp.asarray(labels),
         "segment_ids": jnp.zeros_like(jnp.asarray(tokens))}

    compiled = step.lower(state, b).compile()
    # Same timing discipline as bench.py (median-of-5 windows, host-fetch
    # barriers): the deltas measured here (+3%-ish) are smaller than the
    # 15% one-window tunnel excursions bench.py documents.
    from bench import _time_steps
    # (state buffers are donated inside the timing loop — no further calls
    # on the original state are legal afterwards.)
    sps, spread = _time_steps(compiled, state, b, steps, 60.0)
    return {"variant": variant["name"],
            "tokens_per_sec": round(batch * seq * sps, 1),
            "spread": round(spread, 4)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--tiny", action="store_true",
                    help="CPU harness smoke (numbers meaningless)")
    args = ap.parse_args()
    if args.tiny:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    for v in VARIANTS:
        print(json.dumps(measure(v, args.steps, args.tiny)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
