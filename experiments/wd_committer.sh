#!/bin/bash
# Companion to chip_watchdog.sh: whenever new measurement output lands in
# artifacts/, commit it so a banked number can never be lost to a session
# stall. One commit per sweep covering every changed artifact (steps share
# ledger files, so per-step commits would race); completion is judged by
# the watchdog's own markers, not git history. Exits when every step is
# resolved (done or given up) and the last sweep found nothing to commit.
#
# The commit is pathspec-limited to the ledger files (ADVICE r4): anything
# an operator has staged in the shared index stays staged and untouched.
set -u
cd "$(dirname "$0")/.."

ARTIFACTS=(artifacts/rn50_stages_r05.txt artifacts/bench_r05_live.json
           artifacts/gpt2_tune_r05.jsonl artifacts/bert_ab_r05.jsonl
           artifacts/rn50_variants_r05.jsonl artifacts/mlp_profile_r05.txt
           artifacts/graph_gpt2_r05.jsonl artifacts/rn50_breakdown_r05.txt
           artifacts/sp_smoke_r05.log artifacts/longctx_r05.log)
STEPS=(rn50_stages bench_full gpt2_ab bert_ab rn50_s2d_b256 rn50_remat gpt2_scan
       gpt2_rest mlp_profile graph_gpt2 rn50_nodonate rn50_probe
       sp_smoke longctx)

all_resolved() {
  for s in "${STEPS[@]}"; do
    [ -e "artifacts/wd_done_r05/$s" ] || [ -e "artifacts/wd_done_r05/$s.givenup" ] \
      || return 1
  done
  return 0
}

changed() {  # any artifact new or modified vs HEAD?
  for f in "${ARTIFACTS[@]}"; do
    [ -e "$f" ] || continue
    if ! git ls-files --error-unmatch "$f" >/dev/null 2>&1; then
      return 0  # exists but untracked
    fi
    git diff --quiet HEAD -- "$f" || return 0
  done
  return 1
}

while :; do
  if changed; then
    # Pathspec-limit the commit to the artifacts that EXIST this sweep —
    # listing not-yet-created files makes git abort with "pathspec did
    # not match" and would block banking everything else.
    existing=()
    for f in "${ARTIFACTS[@]}"; do
      [ -e "$f" ] && existing+=("$f")
    done
    if [ "${#existing[@]}" -gt 0 ]; then
      git add -- "${existing[@]}" 2>/dev/null
      git commit -q -m "wd-commit: bank chip measurement artifacts" -- "${existing[@]}" &&
        echo "$(date -u +%H:%M:%SZ) committed banked artifacts"
    fi
  fi
  if all_resolved && ! changed; then
    break
  fi
  sleep 120
done
echo "$(date -u +%H:%M:%SZ) all measurements resolved and committed"
