#!/bin/bash
# Companion to chip_watchdog.sh: whenever a measurement step lands (its
# marker appears in artifacts/wd_done/), commit the corresponding artifact
# so a banked number can never be lost to a session stall. Exits when all
# steps are committed.
set -u
cd "$(dirname "$0")/.."

declare -A FILES=(
  [gpt2_ab]="artifacts/gpt2_tune_r04.jsonl"
  [bert_ab]="artifacts/bert_ab_r04.jsonl"
  [rn50_s2d_b256]="artifacts/rn50_variants_r04.jsonl"
  [gpt2_rest]="artifacts/gpt2_tune_r04.jsonl"
  [rn50_nodonate]="artifacts/rn50_variants_r04.jsonl"
  [rn50_probe]="artifacts/rn50_breakdown_r04.txt"
  [rn50_stages]="artifacts/rn50_stages_r04.txt"
  [sp_smoke]="artifacts/sp_smoke_r04.log"
  [longctx]="artifacts/longctx_r04.log"
)

committed() { git log --oneline -20 | grep -q "wd-commit: $1"; }

while :; do
  all=1
  for s in "${!FILES[@]}"; do
    if [ -e "artifacts/wd_done/$s" ] && ! committed "$s"; then
      git add "${FILES[$s]}" 2>/dev/null
      git commit -q -m "wd-commit: $s measurement banked (${FILES[$s]})" \
        2>/dev/null && echo "$(date -u +%H:%M:%SZ) committed $s"
    fi
    [ -e "artifacts/wd_done/$s" ] && committed "$s" || all=0
  done
  [ "$all" = 1 ] && break
  sleep 120
done
echo "$(date -u +%H:%M:%SZ) all measurements committed"
