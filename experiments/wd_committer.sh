#!/bin/bash
# Companion to chip_watchdog.sh: whenever new measurement output lands in
# artifacts/, commit it so a banked number can never be lost to a session
# stall. One commit per sweep covering every changed artifact (steps share
# ledger files, so per-step commits would race); completion is judged by
# the watchdog's own markers, not git history. Exits when every step is
# resolved (done or given up) and the last sweep found nothing to commit.
set -u
cd "$(dirname "$0")/.."

ARTIFACTS=(artifacts/gpt2_tune_r04.jsonl artifacts/bert_ab_r04.jsonl
           artifacts/rn50_variants_r04.jsonl artifacts/rn50_breakdown_r04.txt
           artifacts/rn50_stages_r04.txt artifacts/sp_smoke_r04.log
           artifacts/longctx_r04.log)
STEPS=(gpt2_ab bert_ab rn50_s2d_b256 gpt2_rest rn50_nodonate rn50_probe
       rn50_stages sp_smoke longctx)

all_resolved() {
  for s in "${STEPS[@]}"; do
    [ -e "artifacts/wd_done/$s" ] || [ -e "artifacts/wd_done/$s.givenup" ] \
      || return 1
  done
  return 0
}

while :; do
  for f in "${ARTIFACTS[@]}"; do
    [ -e "$f" ] && git add "$f" 2>/dev/null
  done
  if ! git diff --cached --quiet; then
    git commit -q -m "wd-commit: bank chip measurement artifacts" &&
      echo "$(date -u +%H:%M:%SZ) committed banked artifacts"
  fi
  if all_resolved && git diff --cached --quiet; then
    break
  fi
  sleep 120
done
echo "$(date -u +%H:%M:%SZ) all measurements resolved and committed"
