"""Graph-IR engine vs module engine on GPT-2 — the VERDICT r4 item-6
measurement: is the StableHLO-lowered IR path within 5% of the module
path, or is it a correctness/portability engine with a quantified gap?

Four points, one JSON line each (bench.py timing discipline):

  - module_bf16:  the production module config (bf16 policy, Pallas flash
                  attention, fused logsumexp head) — the number of record.
  - module_fp32_xla: module engine configured like today's IR program
                  (fp32 policy, composed XLA attention, dense fp32-logit
                  CE) — isolates ENGINE overhead from FEATURE gap.
  - graph_ir_float32:  gpt2_loss_graph + IR-authored AdamW update
                  (graph/programs.py), StableHLO via graph/lower.py.
  - graph_ir_bfloat16: the same program with the bf16 compute policy
                  authored as IR cast nodes AND the fused logsumexp head
                  (bf16 logits, fp32 upcast fused into the reductions) —
                  feature-matched to module_bf16; both IR points emit
                  the flash_attention node.

If graph_ir_float32 ~= module_fp32_xla, the IR engine itself is sound;
graph_ir_bfloat16 then shows how much of module_bf16's lead the IR
recovers with the policy authored in casts, and the residual gap is the
fused head (+ any engine overhead). The conclusion goes to
BENCH_NOTES.md and docs/DESIGN.md.

Usage: python experiments/graph_bench.py [--steps 12] [--batch 8] [--seq 1024]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _flops(cfg, n_params: int, batch: int, seq: int) -> float:
    return (6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq) \
        * batch * seq


def measure_module(name: str, batch: int, seq: int, steps: int, tiny: bool,
                   bf16: bool) -> dict:
    import jax
    import numpy as np
    import jax.numpy as jnp

    from nezha_tpu import optim
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config, lm_loss
    from nezha_tpu.tensor import bf16_policy
    from nezha_tpu.tensor.policy import DEFAULT_POLICY
    from nezha_tpu.train.loop import init_train_state, make_train_step

    small = dict(vocab_size=256, max_positions=max(seq, 64), num_layers=2,
                 num_heads=4, hidden_size=64) if tiny else {}
    if bf16:
        cfg = GPT2Config(fused_loss_chunk=-1, **small)
        model = GPT2(cfg, policy=bf16_policy())
    else:  # mirror today's IR program: fp32, composed attention, dense CE
        cfg = GPT2Config(attn_impl="xla", **small)
        model = GPT2(cfg, policy=DEFAULT_POLICY)
    opt = optim.adamw(6e-4, weight_decay=0.1)
    state = init_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, opt, lm_loss)
    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    b = {"tokens": jnp.asarray(tokens)}

    from bench import _time_steps
    sps, spread = _time_steps(step, state, b, steps, 90.0)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(
        state["variables"]["params"]))
    return {"engine": name, "tokens_per_sec": round(batch * seq * sps, 1),
            "mfu": round(_flops(cfg, n_params, batch, seq) * sps / 197e12, 4),
            "spread": round(spread, 4)}


def measure_graph(batch: int, seq: int, steps: int, tiny: bool,
                  compute_dtype: str = "float32") -> dict:
    import jax
    import numpy as np

    from nezha_tpu.graph import programs
    from nezha_tpu.models.gpt2 import GPT2, GPT2Config

    small = dict(vocab_size=256, max_positions=max(seq, 64), num_layers=2,
                 num_heads=4, hidden_size=64) if tiny else {}
    cfg = GPT2Config(**small)
    model = GPT2(cfg)  # fp32 default policy — what the IR program mirrors
    state = programs.init_graph_gpt2_state(model, jax.random.PRNGKey(0))
    step = programs.make_gpt2_graph_train_step(model, lambda t: 6e-4,
                                               weight_decay=0.1,
                                               compute_dtype=compute_dtype)
    shard = programs.lm_shard_fn()
    tokens = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    b = shard({"tokens": tokens})

    from bench import _time_steps
    sps, spread = _time_steps(step, state, b, steps, 120.0)
    n_params = sum(np.size(x) for x in jax.tree_util.tree_leaves(
        state["params"]))
    return {"engine": f"graph_ir_{compute_dtype}",
            "tokens_per_sec": round(batch * seq * sps, 1),
            "mfu": round(_flops(cfg, n_params, batch, seq) * sps / 197e12, 4),
            "spread": round(spread, 4)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--tiny", action="store_true",
                    help="seconds-scale CPU smoke of the harness")
    args = ap.parse_args()
    if args.tiny:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from nezha_tpu.utils import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    for fn in (lambda: measure_module("module_bf16", args.batch, args.seq,
                                      args.steps, args.tiny, bf16=True),
               lambda: measure_module("module_fp32_xla", args.batch,
                                      args.seq, args.steps, args.tiny,
                                      bf16=False),
               lambda: measure_graph(args.batch, args.seq, args.steps,
                                     args.tiny),
               lambda: measure_graph(args.batch, args.seq, args.steps,
                                     args.tiny,
                                     compute_dtype="bfloat16")):
        print(json.dumps(fn()), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
